"""Mixed precision (compute_dtype=bfloat16): bf16 activations/layer params,
f32 master weights + losses + optimizer — the TPU-first training recipe
(MXU-native dtype; beyond the reference's f32-only scope)."""

import os
import sys

import numpy as np
import jax.numpy as jnp

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from cxxnet_tpu import api

CFG = """
netconfig = start
layer[+1:cv1] = conv:cv1
  kernel_size = 3
  nchannel = 8
  init_sigma = 0.05
layer[+1] = relu
layer[+1] = max_pooling
  kernel_size = 2
  stride = 2
layer[+1] = batch_norm
layer[+1] = flatten
layer[+1:fc2] = fullc:fc2
  nhidden = 10
  init_sigma = 0.05
layer[+0] = softmax
netconfig = end
input_shape = 1,8,8
batch_size = 20
eta = 0.1
momentum = 0.9
compute_dtype = bfloat16
"""


def _data():
    rs = np.random.RandomState(0)
    return (rs.rand(20, 1, 8, 8).astype(np.float32),
            rs.randint(0, 10, 20).astype(np.float32))


def test_bf16_trains_and_masters_stay_f32():
    x, y = _data()
    net = api.Net(dev="cpu", cfg=CFG)
    net.init_model()
    for _ in range(200):
        net.update(x, y)
    assert (net.predict(x) == y).mean() >= 0.95
    assert net.get_weight("fc2", "wmat").dtype == np.float32
    for p in net.net_.params:
        for v in p.values():
            assert jnp.asarray(v).dtype == jnp.float32, \
                "master params must stay f32"


def test_bf16_forward_dtypes():
    x, _ = _data()
    net = api.Net(dev="cpu", cfg=CFG)
    net.init_model()
    nn = net.net_.net
    values, _loss = nn.forward(net.net_.params, x, train=False)
    # hidden nodes run bf16; the loss layer's output (last node) is f32
    assert values[1].dtype == jnp.bfloat16           # conv output
    assert values[-1].dtype == jnp.float32           # softmax output
    row_sums = np.asarray(values[-1]).reshape(20, -1).sum(-1)
    np.testing.assert_allclose(row_sums, np.ones(20), rtol=1e-3)


def test_checkpoint_roundtrip_preserves_dtype_config(tmp_path):
    x, y = _data()
    net = api.Net(dev="cpu", cfg=CFG)
    net.init_model()
    net.update(x, y)
    p1 = net.extract(x, "top[-1]")
    path = str(tmp_path / "m.model")
    net.save_model(path)
    # weightless layers (pooling) read their params from the config, so the
    # same config accompanies the model file (reference semantics: the CLI
    # always re-reads the conf; only weighted layers persist LayerParam)
    net2 = api.Net(dev="cpu", cfg=CFG)
    net2.load_model(path)
    p2 = net2.extract(x, "top[-1]")
    np.testing.assert_allclose(np.asarray(p1), np.asarray(p2),
                               rtol=1e-2, atol=1e-2)


def test_bn_moving_average():
    """moving_average=1: EMA running stats update during training, drive
    eval-mode normalization (sound batch-1 inference), persist through
    checkpoints, and stay out of the optimizer/weight ABI."""
    CFG_MA = """
netconfig = start
layer[+1:fc1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.1
layer[+0] = batch_norm
  moving_average = 1
  bn_momentum = 0.8
layer[+1] = relu
layer[+1:fc2] = fullc:fc2
  nhidden = 5
  init_sigma = 0.1
layer[+0] = softmax
netconfig = end
input_shape = 1,1,12
batch_size = 16
eta = 0.1
momentum = 0.0
"""
    rs = np.random.RandomState(0)
    x = rs.rand(16, 12).astype(np.float32) * 3 + 1
    y = rs.randint(0, 5, 16).astype(np.float32)
    net = api.Net(dev="cpu", cfg=CFG_MA)
    net.init_model()
    bn = 1  # layer index of batch_norm
    rm0 = np.asarray(net.net_.params[bn]["running_mean"]).copy()
    for _ in range(200):
        net.update(x, y)
    rm = np.asarray(net.net_.params[bn]["running_mean"])
    assert not np.allclose(rm, rm0), "running stats must move"
    assert (net.predict(x) == y).mean() == 1.0
    # eval uses the running stats: batch-1 output must equal the same row
    # from a full-batch eval (pure batch-stats BN would differ wildly)
    full = np.asarray(net.extract(x, "top[-1]")).reshape(16, -1)
    one = np.asarray(net.extract(x[:1], "top[-1]")).reshape(1, -1)
    np.testing.assert_allclose(one[0], full[0], rtol=1e-5, atol=1e-6)


def test_bn_default_matches_reference_quirk():
    """Default BN (no moving_average): eval recomputes batch statistics, so
    there are no running_* params (reference behavior preserved)."""
    CFG_REF = """
netconfig = start
layer[+1:fc1] = fullc:fc1
  nhidden = 8
  init_sigma = 0.1
layer[+0] = batch_norm
layer[+1:fc2] = fullc:fc2
  nhidden = 5
  init_sigma = 0.1
layer[+0] = softmax
netconfig = end
input_shape = 1,1,12
batch_size = 8
eta = 0.1
"""
    net = api.Net(dev="cpu", cfg=CFG_REF)
    net.init_model()
    assert "running_mean" not in net.net_.params[1]


def test_bn_finetune_from_model_without_stats(tmp_path):
    """Finetuning a moving_average=1 config from a checkpoint saved without
    running stats must keep the freshly initialized stats (merge, not
    replace) and train without error."""
    base_cfg = """
netconfig = start
layer[+1:fc1] = fullc:fc1
  nhidden = 8
  init_sigma = 0.1
layer[+0:bn1] = batch_norm:bn1
layer[+1:fc2] = fullc:fc2
  nhidden = 5
  init_sigma = 0.1
layer[+0] = softmax
netconfig = end
input_shape = 1,1,12
batch_size = 8
eta = 0.1
"""
    net = api.Net(dev="cpu", cfg=base_cfg)
    net.init_model()
    path = str(tmp_path / "nostats.model")
    net.save_model(path)

    from cxxnet_tpu.learn_task import LearnTask
    from cxxnet_tpu.utils import serializer
    ft_cfg = base_cfg.replace("layer[+0:bn1] = batch_norm:bn1",
                              "layer[+0:bn1] = batch_norm:bn1\n"
                              "  moving_average = 1")
    net2 = api.Net(dev="cpu", cfg=ft_cfg)
    net2.init_model()
    from cxxnet_tpu.utils import checkpoint as ckpt
    payload, _ = ckpt.read_verified(path)   # strip the integrity framing
    r = serializer.Reader(payload)
    r.read_int32()  # net_type
    net2.net_.copy_model_from(r)
    assert "running_mean" in net2.net_.params[1]
    x = np.random.RandomState(0).rand(8, 12).astype(np.float32)
    y = np.zeros(8, np.float32)
    net2.update(x, y)  # must not KeyError


EMBED_CFG = """
netconfig = start
layer[+1:emb] = embed:emb
  vocab_size = 2000
  nhidden = 8
  init_sigma = 0.05
layer[+1] = flatten
layer[+1:fc] = fullc:fc
  nhidden = 5
  init_sigma = 0.05
layer[+0] = softmax
netconfig = end
input_shape = 1,1,4
batch_size = 8
eta = 0.1
compute_dtype = bfloat16
"""


def test_bf16_embed_ids_not_corrupted():
    """Token-id input nodes must be exempt from the bf16 compute cast:
    bf16 has 8 mantissa bits, so ids above ~256 would silently round to a
    neighboring vocab row (e.g. 1003 -> 1000)."""
    net = api.Net(dev="cpu", cfg=EMBED_CFG)
    net.init_model()
    nn = net.net_.net
    # ids chosen to be non-representable in bf16
    ids = np.array([[259, 511, 777, 1003],
                    [1999, 1285, 515, 257]] * 4, np.float32)
    x = ids.reshape(8, 1, 1, 4)
    values, _ = nn.forward(net.net_.params, x, train=False)
    emb = np.asarray(values[1], np.float32)     # (b, 8, 1, 4)
    wmat = np.asarray(net.net_.params[0]["wmat"], np.float32)
    want = wmat[ids.astype(np.int64)]           # (b, 4, 8)
    got = np.moveaxis(emb[:, :, 0, :], 1, 2)    # (b, 4, 8)
    np.testing.assert_allclose(
        got, want.astype(jnp.bfloat16).astype(np.float32), atol=1e-6)
