"""Static-analyzer tests (tools/cxxlint.py + utils/lockrank.py).

Each rule id gets a minimal fixture package that triggers it EXACTLY
once, and each rule family gets a clean fixture asserting no false
positive — the analyzer is itself review-critical code, and a silent
false negative (rule stops firing) or a noisy false positive (every PR
fights the linter) are both regressions. Plus: the baseline ratchet
semantics (shrink ok / grow fails / stale entry fails), the runtime
lock-rank inversion diagnostic, and the real-package gates (clean tree,
RANKS is a valid topological order of the extracted lock graph).

Everything here is jax-free and cheap: fixtures are tiny synthetic
packages in tmp_path; the one full-package lint run is shared across the
real-tree assertions (tier-1 runs near its 870s budget).
"""

import json
import os
import sys
import threading

import pytest

from cxxnet_tpu.utils import lockrank

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
import cxxlint  # noqa: E402


# ----------------------------------------------------------------------
# fixture plumbing
def lint_snippet(tmp_path, files, docs=None):
    """Lint a synthetic package: files maps relpath -> source under
    fixpkg/, docs maps name.md -> markdown (empty doc dir = conf rules
    off, so unrelated fixtures cannot trip the registry)."""
    pkg = tmp_path / "fixpkg"
    pkg.mkdir(exist_ok=True)
    for rel, src in files.items():
        p = pkg / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src, encoding="utf-8")
    docdir = tmp_path / "doc"
    docdir.mkdir(exist_ok=True)
    for name, text in (docs or {}).items():
        (docdir / name).write_text(text, encoding="utf-8")
    return cxxlint.run_lint(str(tmp_path), "fixpkg", str(docdir))


def rules_of(res):
    return [f.rule for f in res.findings]


def assert_fires_once(res, rule):
    rules = rules_of(res)
    assert rules.count(rule) == 1, \
        "%s fired %d times: %r" % (rule, rules.count(rule),
                                   [f.render(os.sep) for f in res.findings])
    assert rules == [rule], "extra findings rode along: %r" % rules
    f = [x for x in res.findings if x.rule == rule][0]
    assert f.line > 0 and f.path
    assert cxxlint.HINTS[rule]   # every rule ships a fix hint


# ----------------------------------------------------------------------
# family (a): concurrency
def test_lock_blocking_fires(tmp_path):
    res = lint_snippet(tmp_path, {"w.py": (
        "import threading\n"
        "import time\n"
        "class Worker:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def slow(self):\n"
        "        with self._lock:\n"
        "            time.sleep(0.1)\n")})
    assert_fires_once(res, "lock-blocking")
    f = res.findings[0]
    assert "time.sleep" in f.msg and "_lock" in f.msg


def test_lock_blocking_through_a_call(tmp_path):
    # the blocking op hides one resolvable call away: the closure over
    # the call graph must still surface it, naming the origin site
    res = lint_snippet(tmp_path, {"w.py": (
        "import threading\n"
        "import time\n"
        "def flush_to_disk(buf):\n"
        "    time.sleep(0.5)\n"
        "class Worker:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def slow(self):\n"
        "        with self._lock:\n"
        "            flush_to_disk([])\n")})
    # direct finding inside flush? no lock held there — exactly the
    # call-site finding must fire
    assert rules_of(res) == ["lock-blocking"]
    assert "flush_to_disk" in res.findings[0].msg


def test_lock_cycle_across_two_classes(tmp_path):
    # two independent call paths, opposite orders: A.outer takes
    # la then B's lb; B.rev takes lb then A's la — a 2-cycle neither
    # class can see alone
    res = lint_snippet(tmp_path, {"ab.py": (
        "import threading\n"
        "class A:\n"
        "    def __init__(self):\n"
        "        self._la = threading.Lock()\n"
        "        self.b = B()\n"
        "    def outer(self):\n"
        "        with self._la:\n"
        "            self.b.poke()\n"
        "    def inner(self):\n"
        "        with self._la:\n"
        "            pass\n"
        "class B:\n"
        "    def __init__(self):\n"
        "        self._lb = threading.Lock()\n"
        "        self.a = A()\n"
        "    def poke(self):\n"
        "        with self._lb:\n"
        "            pass\n"
        "    def rev(self):\n"
        "        with self._lb:\n"
        "            self.a.inner()\n")})
    assert_fires_once(res, "lock-cycle")
    msg = res.findings[0].msg
    assert "_la" in msg and "_lb" in msg


def test_lock_self_cycle_is_a_deadlock(tmp_path):
    res = lint_snippet(tmp_path, {"re.py": (
        "import threading\n"
        "class R:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def get(self):\n"
        "        with self._lock:\n"
        "            return self.peek()\n"
        "    def peek(self):\n"
        "        with self._lock:\n"
        "            return 1\n")})
    assert_fires_once(res, "lock-cycle")


def test_thread_unjoined_fires(tmp_path):
    res = lint_snippet(tmp_path, {"t.py": (
        "import threading\n"
        "def spawn():\n"
        "    t = threading.Thread(target=print)\n"
        "    t.start()\n"
        "    return t\n")})
    assert_fires_once(res, "thread-unjoined")


def test_thread_unjoined_not_fooled_by_suffix_join(tmp_path):
    # regression: the join-detection needs a left word boundary —
    # client.join(",") must not count as joining a thread named t
    res = lint_snippet(tmp_path, {"t.py": (
        "import threading\n"
        "def spawn(client):\n"
        "    t = threading.Thread(target=print)\n"
        "    t.start()\n"
        "    client.join(',')\n"
        "    return t\n")})
    assert rules_of(res) == ["thread-unjoined"]


def test_lock_rank_contradiction_fires(tmp_path):
    # the fixture's own RANKS table inverts the acquisition order the
    # code actually uses — the static rule must catch the drift before
    # the runtime checker starts raising in production
    res = lint_snippet(tmp_path, {
        "utils/lockrank.py": 'RANKS = {"fix.a": 20, "fix.b": 10}\n',
        "m.py": (
            "from .utils import lockrank\n"
            "class M:\n"
            "    def __init__(self):\n"
            '        self._a = lockrank.lock("fix.a")\n'
            '        self._b = lockrank.lock("fix.b")\n'
            "    def both(self):\n"
            "        with self._a:\n"
            "            with self._b:\n"
            "                pass\n")})
    assert_fires_once(res, "lock-rank")
    assert "fix.a" in res.findings[0].msg \
        and "fix.b" in res.findings[0].msg


def test_concurrency_clean_no_false_positive(tmp_path):
    res = lint_snippet(tmp_path, {"ok.py": (
        "import threading\n"
        "import time\n"
        "class Clean:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._t = threading.Thread(target=print, daemon=True)\n"
        "    def fast(self):\n"
        "        with self._lock:\n"
        "            x = 1 + 1\n"
        "        time.sleep(0.0)  # blocking AFTER release is fine\n"
        "        return x\n"
        "def run():\n"
        "    t = threading.Thread(target=print)\n"
        "    t.start()\n"
        "    t.join()\n")})
    assert rules_of(res) == []


# ----------------------------------------------------------------------
# family (b): jax hazards
def test_wallclock_fires(tmp_path):
    res = lint_snippet(tmp_path, {"c.py": (
        "import time\n"
        "def took():\n"
        "    t0 = time.time()\n"
        "    return t0\n")})
    assert_fires_once(res, "wallclock")


def test_wallclock_suppressed_with_reason(tmp_path):
    res = lint_snippet(tmp_path, {"c.py": (
        "import time\n"
        "def stamp():\n"
        "    # cxxlint: disable=wallclock — epoch for humans, never "
        "subtracted\n"
        "    return time.time()\n")})
    assert rules_of(res) == []
    assert [f.rule for f in res.suppressed] == ["wallclock"]


def test_inline_suppression_does_not_cover_next_line(tmp_path):
    # regression: an inline suppression covers its own line ONLY — a
    # fresh violation added directly under an existing suppression must
    # still surface (it used to be silently absorbed)
    res = lint_snippet(tmp_path, {"c.py": (
        "import time\n"
        "def f():\n"
        "    t0 = time.time()  # cxxlint: disable=wallclock — epoch\n"
        "    t1 = time.time()\n"
        "    return t0, t1\n")})
    assert rules_of(res) == ["wallclock"]
    assert res.findings[0].line == 4
    assert [s.line for s in res.suppressed] == [3]


def test_suppression_without_reason_is_a_finding(tmp_path):
    res = lint_snippet(tmp_path, {"c.py": (
        "import time\n"
        "def stamp():\n"
        "    return time.time()  # cxxlint: disable=wallclock\n")})
    assert rules_of(res) == ["bad-suppression"]


def test_donated_reuse_fires(tmp_path):
    res = lint_snippet(tmp_path, {"d.py": (
        "import jax\n"
        "def step(params, grads):\n"
        "    fn = jax.jit(apply, donate_argnums=0)\n"
        "    out = fn(params, grads)\n"
        "    return params\n")})
    assert_fires_once(res, "donated-reuse")
    assert "params" in res.findings[0].msg


def test_traced_branch_fires(tmp_path):
    res = lint_snippet(tmp_path, {"j.py": (
        "import jax\n"
        "@jax.jit\n"
        "def absval(x):\n"
        "    if x > 0:\n"
        "        return x\n"
        "    return -x\n")})
    assert_fires_once(res, "traced-branch")
    assert "absval" in res.findings[0].msg


def test_timed_dispatch_fires(tmp_path):
    res = lint_snippet(tmp_path, {"s.py": (
        "import jax\n"
        "from .utils import telemetry\n"
        "def bench(xs):\n"
        "    fn = jax.jit(compute)\n"
        '    with telemetry.span("bench.step"):\n'
        "        out = fn(xs)\n"
        "    return out\n")})
    assert_fires_once(res, "timed-dispatch")


def test_jax_clean_no_false_positive(tmp_path):
    res = lint_snippet(tmp_path, {"ok.py": (
        "import time\n"
        "import jax\n"
        "import jax.numpy as jnp\n"
        "from .utils import telemetry\n"
        "def step(params, grads):\n"
        "    fn = jax.jit(apply, donate_argnums=0)\n"
        "    params = fn(params, grads)   # rebound: donation is safe\n"
        "    return params\n"
        "@jax.jit\n"
        "def absval(x):\n"
        "    return jnp.where(x > 0, x, -x)\n"
        "def bench(xs):\n"
        "    fn = jax.jit(compute)\n"
        "    t0 = time.monotonic()\n"
        '    with telemetry.span("bench.step"):\n'
        "        out = jax.block_until_ready(fn(xs))\n"
        "    return out, time.monotonic() - t0\n")})
    assert rules_of(res) == []


# ----------------------------------------------------------------------
# family (c): conf-key registry
CONF_READER = (
    "class Net:\n"
    "    def set_param(self, name, val):\n"
    '        if name == "alpha":\n'
    "            self.alpha = float(val)\n"
    '        if name == "beta":\n'
    "            self.beta = float(val)\n")

CONF_DOC = ("# keys\n\n"
            "| key | meaning |\n"
            "|---|---|\n"
            "| `alpha` | step size |\n")


def test_conf_undocumented_fires(tmp_path):
    res = lint_snippet(tmp_path, {"n.py": CONF_READER},
                       docs={"global.md": CONF_DOC})
    assert_fires_once(res, "conf-undocumented")
    assert "beta" in res.findings[0].msg


def test_conf_dead_fires(tmp_path):
    res = lint_snippet(
        tmp_path, {"n.py": CONF_READER},
        docs={"global.md": CONF_DOC + "| `beta` | momentum |\n"
                                      "| `gamma` | unused relic |\n"})
    assert_fires_once(res, "conf-dead")
    assert "gamma" in res.findings[0].msg


def test_conf_clean_no_false_positive(tmp_path):
    res = lint_snippet(
        tmp_path, {"n.py": CONF_READER},
        docs={"global.md": CONF_DOC + "| `beta` | momentum |\n"})
    assert rules_of(res) == []


# ----------------------------------------------------------------------
# family (d): metric registry
def test_metric_name_fires(tmp_path):
    res = lint_snippet(tmp_path, {"m.py": (
        "from .utils import telemetry\n"
        "def f():\n"
        '    telemetry.count("serve requests!")\n')})
    assert_fires_once(res, "metric-name")


def test_metric_type_fires(tmp_path):
    res = lint_snippet(tmp_path, {"m.py": (
        "from .utils import telemetry\n"
        "def f():\n"
        '    telemetry.count("serve.load")\n'
        '    telemetry.gauge("serve.load")\n')})
    assert_fires_once(res, "metric-type")


def test_metric_suffix_fires(tmp_path):
    # statusd appends _total to counters at scrape time: a raw name
    # already carrying it would render serve_requests_total_total
    res = lint_snippet(tmp_path, {"m.py": (
        "from .utils import telemetry\n"
        "def f():\n"
        '    telemetry.count("serve.requests_total")\n')})
    assert_fires_once(res, "metric-suffix")


def test_metric_collision_fires(tmp_path):
    res = lint_snippet(tmp_path, {"m.py": (
        "from .utils import telemetry\n"
        "def f():\n"
        '    telemetry.count("serve.reqs")\n'
        '    telemetry.count("serve/reqs")\n')})
    assert_fires_once(res, "metric-collision")


def test_metric_clean_no_false_positive(tmp_path):
    res = lint_snippet(tmp_path, {"m.py": (
        "from .utils import telemetry\n"
        "def f(dt):\n"
        '    telemetry.count("serve.requests")\n'
        '    telemetry.gauge("serve.queue_depth", 3)\n'
        '    telemetry.hist("serve.request", dt)\n')})
    assert rules_of(res) == []


def test_metric_doc_fires_on_undocumented_series(tmp_path):
    # an exported series missing from the doc metric tables — named by
    # its SCRAPE name (sanitized + type suffix), what an operator greps
    res = lint_snippet(tmp_path, {"m.py": (
        "from .utils import telemetry\n"
        "def f():\n"
        '    telemetry.count("serve.requests")\n')},
        docs={"observability.md": "no metric tables here\n"})
    assert_fires_once(res, "metric-doc")
    assert "cxxnet_serve_requests_total" in res.findings[0].msg


def test_metric_doc_latch_without_clear_fires(tmp_path):
    # a transition-latch event with a set site but no constant clear
    # site: the timeline would open episodes that never end
    res = lint_snippet(tmp_path, {
        "autopsy.py":
            'TRANSITION_EVENTS = {"kv_pressure": "pressure"}\n',
        "m.py": (
            "from .utils import telemetry\n"
            "def f():\n"
            '    telemetry.event({"ev": "kv_pressure",'
            ' "pressure": 1})\n')},
        docs={"observability.md": "x\n"})
    assert_fires_once(res, "metric-doc")
    assert "kv_pressure" in res.findings[0].msg


def test_metric_doc_clean_no_false_positive(tmp_path):
    res = lint_snippet(tmp_path, {
        "autopsy.py":
            'TRANSITION_EVENTS = {"kv_pressure": "pressure"}\n',
        "m.py": (
            "from .utils import telemetry\n"
            "def f():\n"
            '    telemetry.count("serve.requests")\n'
            '    telemetry.event({"ev": "kv_pressure",'
            ' "pressure": 1})\n'
            '    telemetry.event({"ev": "kv_pressure",'
            ' "pressure": 0})\n')},
        docs={"observability.md":
              "| `cxxnet_serve_requests_total` | door count |\n"})
    assert rules_of(res) == []


def test_metric_doc_off_without_doc_files(tmp_path):
    # neither observability.md nor serving.md in the doc dir: the rule
    # is OFF (synthetic fixture packages must not drown in findings),
    # exactly like the conf registry with no global.md
    res = lint_snippet(tmp_path, {"m.py": (
        "from .utils import telemetry\n"
        "def f():\n"
        '    telemetry.count("serve.requests")\n')})
    assert rules_of(res) == []


# ----------------------------------------------------------------------
# baseline ratchet
def fp(rule, n):
    return cxxlint.Finding(rule, os.path.join(REPO, "x.py"), n,
                           "seeded", key="k%d" % n)


def test_ratchet_clean_baseline_passes():
    new, grand, stale = cxxlint.ratchet([], REPO, {})
    assert (new, grand, stale) == ([], [], [])


def test_ratchet_grandfathers_exactly_the_baseline():
    f1 = fp("wallclock", 1)
    base = {f1.fingerprint(REPO): 1}
    new, grand, stale = cxxlint.ratchet([f1], REPO, base)
    assert new == [] and grand == [f1] and stale == []


def test_ratchet_growth_fails():
    f1, f2 = fp("wallclock", 1), fp("wallclock", 2)
    base = {f1.fingerprint(REPO): 1}
    new, grand, stale = cxxlint.ratchet([f1, f2], REPO, base)
    assert new == [f2] and grand == [f1] and stale == []


def test_ratchet_stale_entry_fails():
    # the violation was fixed but the baseline still grandfathers it:
    # the debt entry must shrink with the debt, or the ratchet is soft
    f1 = fp("wallclock", 1)
    base = {f1.fingerprint(REPO): 1, "wallclock|gone.py|k9": 1}
    new, grand, stale = cxxlint.ratchet([f1], REPO, base)
    assert new == [] and stale == ["wallclock|gone.py|k9"]


def test_ratchet_count_shrink_is_stale_too():
    f1 = fp("wallclock", 1)
    base = {f1.fingerprint(REPO): 1}
    base[f1.fingerprint(REPO)] = 2     # baseline says two, tree has one
    new, grand, stale = cxxlint.ratchet([f1], REPO, base)
    assert new == [] and stale == [f1.fingerprint(REPO)]


def test_update_baseline_round_trips(tmp_path, monkeypatch):
    # --update-baseline writes what ratchet() then accepts verbatim
    findings = [fp("wallclock", 1), fp("wallclock", 1)]
    counts = cxxlint.counts_of(findings, REPO)
    path = tmp_path / "base.json"
    path.write_text(json.dumps(counts), encoding="utf-8")
    loaded = cxxlint.load_baseline(str(path))
    new, grand, stale = cxxlint.ratchet(findings, REPO, loaded)
    assert new == [] and stale == [] and len(grand) == 2


# ----------------------------------------------------------------------
# runtime lock-rank enforcement
def test_lockrank_inversion_names_both_locks_and_sites(monkeypatch):
    monkeypatch.setenv("CXXNET_LOCKRANK", "1")
    outer = lockrank.lock("servd.queue")        # rank 10
    inner = lockrank.lock("telemetry.registry")  # rank 100
    with outer:
        with inner:
            pass                                 # in order: silent
    assert lockrank.held() == []
    with pytest.raises(lockrank.LockOrderError) as ei:
        with inner:
            with outer:                          # inversion
                pass
    msg = str(ei.value)
    assert "servd.queue" in msg and "telemetry.registry" in msg
    assert msg.count(".py:") >= 2, \
        "diagnostic must carry both acquisition sites: " + msg
    assert lockrank.held() == [], "stack leaked after the raise"
    # a condition-entered inversion reports THIS file as the site, not
    # the threading.py internals the acquisition tunnels through
    cond = lockrank.condition("servd.conn")      # rank 30
    with pytest.raises(lockrank.LockOrderError) as ei2:
        with inner:                              # rank 100
            with cond:
                pass
    assert "threading.py" not in str(ei2.value), str(ei2.value)
    assert "test_cxxlint.py" in str(ei2.value)
    assert lockrank.held() == []


def test_lockrank_off_is_silent_and_late_enable_enforces(monkeypatch):
    monkeypatch.delenv("CXXNET_LOCKRANK", raising=False)
    # enforcement is gated per ACQUISITION, not at construction
    a, b = lockrank.lock("telemetry.registry"), lockrank.lock("servd.queue")
    with a:
        with b:
            pass             # inverted order silent when off
    assert lockrank.held() == []
    # the SAME objects enforce once the env flips on — import-time
    # singletons (the module-level telemetry registry) must not escape
    monkeypatch.setenv("CXXNET_LOCKRANK", "1")
    with pytest.raises(lockrank.LockOrderError):
        with a:
            with b:
                pass
    assert lockrank.held() == []


def test_module_level_telemetry_registry_lock_is_enforced(monkeypatch):
    # the innermost lock of the whole rank table is built at telemetry
    # import time, long before any test or selftest can flip the env —
    # it must still participate in enforcement
    from cxxnet_tpu.utils import telemetry
    assert isinstance(telemetry._REG._lock, lockrank.RankedLock)
    monkeypatch.setenv("CXXNET_LOCKRANK", "1")
    with pytest.raises(lockrank.LockOrderError):
        with telemetry._REG._lock:
            with lockrank.lock("servd.queue"):   # 100 -> 10: inversion
                pass
    assert lockrank.held() == []


def test_lockrank_condition_wait_keeps_stack_honest(monkeypatch):
    monkeypatch.setenv("CXXNET_LOCKRANK", "1")
    cond = lockrank.condition("servd.conn")      # rank 30
    inner = lockrank.lock("servd.stats")         # rank 50
    done = []

    def waiter():
        with cond:
            while not done:
                cond.wait(1.0)
            with inner:                          # re-take kept rank 30
                done.append("ok")

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    import time
    time.sleep(0.05)
    with cond:
        done.append("go")
        cond.notify()
    t.join(2.0)
    assert "ok" in done
    # regression: every wait() used to leak a phantom held-lock entry
    # on the waiting thread (Condition.__init__ binds acquire/release
    # from the inner lock as instance attributes, shadowing subclass
    # overrides) — a later in-order acquisition then raised a bogus
    # self-inversion
    with cond:
        cond.wait(0.01)          # timed-out wait on THIS thread
    assert lockrank.held() == [], \
        "condition wait leaked: %r" % lockrank.held()
    with lockrank.lock("servd.queue"):   # would raise on the leak
        pass


# ----------------------------------------------------------------------
# the real package
@pytest.fixture(scope="module")
def real_lint():
    return cxxlint.run_lint()


def test_real_tree_parses_and_is_clean(real_lint):
    assert real_lint.project.parse_errors == []
    assert len(real_lint.project.modules) > 10
    baseline = cxxlint.load_baseline(cxxlint.BASELINE)
    new, _, stale = cxxlint.ratchet(real_lint.findings, cxxlint.ROOT,
                                    baseline)
    assert new == [], "\n".join(f.render(cxxlint.ROOT) for f in new)
    assert stale == [], "stale baseline entries: %r" % stale


def test_real_suppressions_all_carry_reasons(real_lint):
    # every shipped suppression documents why (bad-suppression covers
    # the mechanics; this asserts the tree actually uses it)
    assert real_lint.suppressed, "expected shipped suppressions"
    for mod in real_lint.project.modules.values():
        for line, (rules, reason) in mod.suppress.items():
            if cxxlint.SUPPRESS_RE.search(mod.lines[line - 1] or ""):
                assert reason, "%s:%d suppression has no reason" \
                    % (mod.path, line)


def test_ranks_are_a_topological_order_of_the_real_graph(real_lint):
    # the runtime table and the static graph must agree, or lockrank
    # raises on orderings the analyzer proved safe (and vice versa)
    edges = real_lint.edges
    assert edges, "lock graph came out empty — resolution broke"
    for (a, b) in edges:
        ra = lockrank.RANKS.get(a)
        rb = lockrank.RANKS.get(b)
        if ra is not None and rb is not None:
            assert ra < rb, \
                "edge %s -> %s contradicts RANKS (%d >= %d)" \
                % (a, b, ra, rb)
    # and the graph the doc tells people to inspect is printable
    order = cxxlint.topo_ranks(edges)
    assert set(order) == {n for e in edges for n in e}


# ----------------------------------------------------------------------
# err-vocab: every ERR string servd/routerd can emit must be a row of
# serving.md's error-vocabulary table (the wire contract the fleet
# router dispatches retry/replay/relay on)

ERR_DOC = (
    "# serving\n\n### Error vocabulary\n\n"
    "| error line | meaning |\n|---|---|\n"
    "| `ERR busy queue full (N)` | shed |\n"
    "| `ERR busy tenant <t> over fair share ...` | fair-share shed |\n"
    "| `ERR backend ...` | backend raised |\n\n"
    "## next section\n\n`ERR bogus thing` outside the table does "
    "not count.\n")


def test_err_vocab_fires_on_undocumented_error_string(tmp_path):
    res = lint_snippet(tmp_path, {"servd.py": (
        'MSG = "ERR wedged backend stuck"\n')},
        docs={"serving.md": ERR_DOC})
    assert_fires_once(res, "err-vocab")


def test_err_vocab_matching_rules(tmp_path):
    # %-format tokens, placeholder/`(N)` doc tokens, `...` tails and
    # code-side prefixes ("ERR backend " + detail) all match; the rule
    # only watches the wire-speaking modules, and a span outside the
    # vocabulary section does not whitelist anything
    res = lint_snippet(tmp_path, {
        "servd.py": (
            'A = "ERR busy queue full (%d)" % 4\n'
            'B = "ERR busy tenant %s over fair share (evicted)"\n'
            'C = "ERR backend " + "boom"\n'
            'D = "ERR %s %s"\n'),
        "other.py": 'E = "ERR wedged not a wire module"\n'},
        docs={"serving.md": ERR_DOC})
    assert "err-vocab" not in rules_of(res)
    res = lint_snippet(tmp_path, {"routerd.py": (
        'F = "ERR bogus thing"\n')},
        docs={"serving.md": ERR_DOC})
    assert_fires_once(res, "err-vocab")


def test_err_vocab_off_without_vocabulary_section(tmp_path):
    # a doc tree without the table (or without serving.md at all)
    # disables the rule instead of flagging every error string
    res = lint_snippet(tmp_path, {"servd.py": (
        'MSG = "ERR wedged backend stuck"\n')},
        docs={"serving.md": "# serving\n\nno table here\n"})
    assert rules_of(res) == []


def test_err_vocab_real_tree_is_clean(real_lint):
    # the shipped servd/routerd error strings are all documented —
    # the baseline carries ZERO err-vocab debt
    assert [f for f in real_lint.findings
            if f.rule == "err-vocab"] == []
