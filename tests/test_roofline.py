"""Analytic roofline accounting (tools/roofline.py): the FLOPs and
decode-bandwidth models the MFU/serving verdicts rest on. Hand-computed
expectations on tiny configs."""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

import roofline
from cxxnet_tpu.models import transformer_lm_trainer


def _lm(seq=16, dim=32, nhead=4, nlayer=1, vocab=8, extra=""):
    return transformer_lm_trainer(vocab=vocab, seq=seq, batch_size=2,
                                  dim=dim, nhead=nhead, nlayer=nlayer,
                                  dev="cpu", extra_cfg=extra)


def test_attention_projection_flops_scale_with_L():
    """ADVICE r4 (medium): wqkv/wo apply per position — projection FLOPs
    must carry the L factor, like conv's Ho*Wo."""
    tr = _lm()
    L, d, vocab, ffn = 16, 32, 8, 64
    f = roofline.net_flops_per_sample(tr)
    # per sample: attention projections 2*L*(3dd + dd) [wqkv d x 3d + wo],
    # scores+AV causal 2*L*L*d, FFN convs 2*L*(d*ffn + ffn*d), head
    # 2*L*d*vocab
    want = (2 * L * (3 * d * d + d * d) + 2 * L * L * d
            + 2 * L * (d * ffn + ffn * d) + 2 * L * d * vocab)
    assert abs(f - want) / want < 0.02, (f, want)


def test_decode_bound_hand_computed():
    """bytes/step = non-embed params + B * (2*kv_dim*min(t,win)*nlayer*2B
    + embed row reads), averaged over generated positions; embed tables
    are a gather (B rows/step), not a full read."""
    tr = _lm()
    B, plen, gen_to = 2, 4, 16
    bound, pbytes = roofline.decode_bound(tr, B, plen, gen_to)
    want_pbytes = 0.0
    want_rows = 0.0
    for lay, p in zip(tr.net.layers, tr.params):
        for w in p.values():
            if getattr(lay, "type_name", "") == "embed":
                want_rows += 2.0 * np.shape(w)[-1]
            else:
                want_pbytes += 2.0 * np.prod(np.shape(w))
    assert pbytes == want_pbytes
    ts = np.arange(plen, gen_to, dtype=float)
    kv = 2.0 * 32 * ts * 2            # 1 layer, kv_dim=d=32, bf16
    step = want_pbytes + B * (kv.mean() + want_rows)
    assert abs(bound - B * roofline.peak_hbm_bytes() / step) < 1e-6


def test_decode_bound_window_caps_kv_read():
    """A sliding window must cap the KV read term: at large L the
    windowed bound stays flat instead of shrinking ~1/L."""
    win = 8
    tr_w = _lm(seq=64, extra="")          # same net; window set below
    tr_d = _lm(seq=64)
    bound_d, _ = roofline.decode_bound(tr_d, 1, 4, 64)
    for lay in tr_w.net.layers:
        if getattr(lay, "type_name", "") == "attention":
            lay.attn_window = win
    bound_w, _ = roofline.decode_bound(tr_w, 1, 4, 64)
    assert bound_w > bound_d
