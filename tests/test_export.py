"""AOT export (jax.export / StableHLO): the inference forward with params
baked in becomes a self-contained serving artifact — loadable with jax
alone, no framework/config/model file. Deployment-story counterpart of the
reference's C-wrapper-plus-model-file flow (wrapper/cxxnet_wrapper.h).
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from cxxnet_tpu import api
from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.nnet.trainer import Trainer
from cxxnet_tpu.utils.config import parse_config_string

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CONV_NET = """
netconfig = start
layer[0->1] = conv:c1
  kernel_size = 3
  nchannel = 6
  random_type = xavier
layer[1->2] = relu
layer[2->feat] = max_pooling
  kernel_size = 2
  stride = 2
layer[feat->4] = flatten
layer[4->5] = fullc:fc
  nhidden = 4
  init_sigma = 0.1
layer[5->5] = softmax
netconfig = end
input_shape = 1,10,10
batch_size = 8
eta = 0.1
dev = cpu
"""


def _trained(extra=""):
    tr = Trainer()
    for k, v in parse_config_string(CONV_NET + extra):
        tr.set_param(k, v)
    tr.init_model()
    rs = np.random.RandomState(0)
    b = DataBatch()
    b.data = rs.rand(8, 1, 10, 10).astype(np.float32)
    b.label = rs.randint(0, 4, (8, 1)).astype(np.float32)
    b.batch_size = 8
    for _ in range(3):
        tr.update(b)
    return tr, b


def test_export_matches_forward(tmp_path):
    tr, b = _trained()
    path = str(tmp_path / "m.stablehlo")
    with open(path, "wb") as f:
        f.write(tr.export_forward())
    fn = api.load_exported(path)
    got = fn(b.data).reshape(8, -1)
    want = np.asarray(tr.extract_feature(b, "top[-1]")).reshape(8, -1)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)


def test_export_named_node_and_batch_override(tmp_path):
    tr, b = _trained()
    path = str(tmp_path / "feat.stablehlo")
    with open(path, "wb") as f:
        f.write(tr.export_forward(node_name="feat", batch_size=4))
    fn = api.load_exported(path)
    got = fn(b.data[:4])
    b4 = DataBatch()
    b4.data = b.data[:4]
    b4.label = b.label[:4]
    b4.batch_size = 4
    want = np.asarray(tr.extract_feature(b4, "feat"))
    np.testing.assert_allclose(np.asarray(got), want[:4],
                               rtol=1e-6, atol=1e-7)


def test_export_symbolic_batch_serves_any_n(tmp_path):
    """batch_size=-1 exports ONE artifact with a symbolic batch dim: it
    serves batch 1, 3, and 8 and matches the per-batch fixed exports."""
    tr, b = _trained()
    path = str(tmp_path / "sym.stablehlo")
    with open(path, "wb") as f:
        f.write(tr.export_forward(batch_size=-1))
    fn = api.load_exported(path)
    want = np.asarray(tr.extract_feature(b, "top[-1]")).reshape(8, -1)
    for n in (1, 3, 8):
        got = np.asarray(fn(b.data[:n])).reshape(n, -1)
        np.testing.assert_allclose(got, want[:n], rtol=1e-5, atol=1e-6)


def test_export_channels_last_artifact_is_nchw(tmp_path):
    """The artifact's contract is reference-NCHW regardless of the
    internal device layout it was exported under."""
    tr, b = _trained(extra="channels_last = 1\n")
    ref, _ = _trained(extra="channels_last = 0\n")
    path = str(tmp_path / "cl.stablehlo")
    with open(path, "wb") as f:
        f.write(tr.export_forward())
    fn = api.load_exported(path)
    got = fn(b.data).reshape(8, -1)
    want = np.asarray(ref.extract_feature(b, "top[-1]")).reshape(8, -1)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_export_runs_without_framework(tmp_path):
    """The serving side needs jax only: a fresh interpreter that never
    imports cxxnet_tpu runs the artifact. The 12-byte-header CXTF frame
    (utils/artifact.py) is unwrapped with two struct reads — the
    documented framework-free recipe from the export_forward docstring."""
    tr, b = _trained()
    path = str(tmp_path / "standalone.stablehlo")
    with open(path, "wb") as f:
        f.write(tr.export_forward())
    np.save(str(tmp_path / "x.npy"), b.data)
    code = (
        "import jax, numpy as np, struct\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from jax import export\n"
        "import sys\n"
        "assert not any(m.startswith('cxxnet') for m in sys.modules)\n"
        "data = open(%r, 'rb').read()\n"
        "assert data[:4] == b'CXTF', 'versioned artifact frame'\n"
        "ver, hlen = struct.unpack('<II', data[4:12])\n"
        "assert ver == 1\n"
        "exp = export.deserialize(data[12 + hlen:])\n"
        "out = exp.call(np.load(%r))\n"
        "np.save(%r, np.asarray(out))\n"
        % (path, str(tmp_path / "x.npy"), str(tmp_path / "y.npy")))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    subprocess.run([sys.executable, "-c", code], check=True, env=env,
                   timeout=300)
    got = np.load(str(tmp_path / "y.npy")).reshape(8, -1)
    want = np.asarray(tr.extract_feature(b, "top[-1]")).reshape(8, -1)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)


def test_export_cli_task(tmp_path):
    """task = export through the CLI: train -> save -> export -> load."""
    from cxxnet_tpu import learn_task
    tr, b = _trained()
    model_path = str(tmp_path / "m.model")
    from cxxnet_tpu.utils import serializer
    w = serializer.Writer()
    w.write_int32(0)   # leading net_type int (learn_task._save_model)
    tr.save_model(w)
    with open(model_path, "wb") as f:
        f.write(w.getvalue())
    conf_path = str(tmp_path / "export.conf")
    out_path = str(tmp_path / "cli.stablehlo")
    with open(conf_path, "w") as f:
        f.write(CONV_NET + "task = export\nmodel_in = %s\n"
                "export_out = %s\n" % (model_path, out_path))
    rc = learn_task.main([conf_path])
    assert rc == 0
    fn = api.load_exported(out_path)
    got = fn(b.data).reshape(8, -1)
    want = np.asarray(tr.extract_feature(b, "top[-1]")).reshape(8, -1)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)
