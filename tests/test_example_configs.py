"""Shipped example configs must parse and build their nets (the reference's
example/ recipes are its integration surface — SURVEY.md §4.4).

Data files aren't present, so iterators are skipped: we parse each conf,
strip the io sections, and run model init + one synthetic update on CPU.
"""

import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.nnet.trainer import create_net
from cxxnet_tpu.utils.config import parse_config_file


def build_from_conf(path, batch_size=4):
    pairs = parse_config_file(path)
    # strip iterator sections (data=/eval=/pred= .. iter=end)
    kept, in_section = [], False
    for k, v in pairs:
        if k in ("data", "eval", "pred"):
            in_section = True
            continue
        if in_section:
            if k == "iter" and v == "end":
                in_section = False
            continue
        kept.append((k, v))
    tr = create_net(0)
    for k, v in kept:
        if k in ("dev", "batch_size", "num_round", "max_round", "save_model",
                 "model_dir", "continue"):
            continue
        tr.set_param(k, v)
    tr.set_param("dev", "cpu")
    tr.set_param("batch_size", str(batch_size))
    tr.init_model()
    return tr, dict(kept)


@pytest.mark.parametrize("conf,shape,nclass", [
    ("example/MNIST/MNIST.conf", (1, 1, 784), 10),
    ("example/MNIST/MNIST_CONV.conf", (1, 28, 28), 10),
    ("example/MNIST/multichip.conf", (1, 1, 784), 10),
    ("example/kaggle_bowl/bowl.conf", (3, 40, 40), 121),
    ("example/ImageNet/ImageNet.conf", (3, 227, 227), 1000),
])
def test_example_conf_builds_and_steps(conf, shape, nclass):
    tr, cfg = build_from_conf(os.path.join(REPO, conf))
    got_shape = tuple(int(x) for x in cfg["input_shape"].split(","))
    assert got_shape == shape, "input_shape drifted from the recipe"
    rs = np.random.RandomState(0)
    b = DataBatch()
    b.data = rs.rand(4, *shape).astype(np.float32)
    b.label = rs.randint(0, nclass, (4, 1)).astype(np.float32)
    b.batch_size = 4
    tr.update(b)
    out = tr.predict(b)
    assert out.shape == (4,)
    assert (0 <= out).all() and (out < nclass).all()


@pytest.mark.slow
def test_googlenet_conf_builds_and_steps():
    # slow tier (tier-1 budget): the conf-parsing path rides tier-1 via
    # the ImageNet/MNIST confs; the inception DAG compile via test_fusion
    """The GoogLeNet example (BASELINE config 4): builds the 9-module
    inception DAG and takes a step at reduced input size."""
    tr, cfg = build_from_conf(
        os.path.join(REPO, "example/ImageNet/GoogLeNet.conf"))
    # shrink: the conf is 224x224; rebuild at 64 via the model zoo to keep
    # the CPU test fast, asserting the conf's netconfig parses above
    from cxxnet_tpu.models import googlenet_trainer
    tr = googlenet_trainer(batch_size=4, input_hw=64, dev="cpu", n_class=10)
    rs = np.random.RandomState(0)
    b = DataBatch()
    b.data = rs.rand(4, 3, 64, 64).astype(np.float32)
    b.label = rs.randint(0, 10, (4, 1)).astype(np.float32)
    b.batch_size = 4
    tr.update(b)
    out = tr.predict(b)
    assert out.shape == (4,)


@pytest.mark.slow
def test_vgg_conf_builds_and_steps():
    # slow tier (tier-1 budget): deep-plain-conv coverage rides tier-1
    # via test_remat's vgg-shaped trunks
    """The VGG-16 example: parses (incl. the remat=1 netcfg default) and a
    reduced vgg11 takes a training step."""
    tr, cfg = build_from_conf(
        os.path.join(REPO, "example/ImageNet/VGG.conf"))
    assert all(l.remat == 1 for l in tr.net.layers)
    from cxxnet_tpu.models import vgg_trainer
    tr = vgg_trainer(batch_size=4, input_hw=32, dev="cpu", n_class=10,
                     arch="vgg11", fc_dim=32, dropout=0.0)
    rs = np.random.RandomState(0)
    b = DataBatch()
    b.data = rs.rand(4, 3, 32, 32).astype(np.float32)
    b.label = rs.randint(0, 10, (4, 1)).astype(np.float32)
    b.batch_size = 4
    tr.update(b)
    out = tr.predict(b)
    assert out.shape == (4,)
