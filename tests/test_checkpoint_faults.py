"""Fault-injection suite for the preemption-tolerant checkpoint subsystem.

Proves the robustness contract end-to-end: for every injected failure —
kill mid-write, truncation, bit flip, torn footer, rename failure, disk
full, stale tmp — training either resumes from the newest VALID checkpoint
or fails loudly with a clear error; no run ever loads garbage. A SIGTERM
mid-round produces an emergency checkpoint from which resume reproduces
the uninterrupted run bit-for-bit on the CPU backend.
"""

import json
import os
import shutil
import signal
import sys

import numpy as np
import pytest

from cxxnet_tpu.learn_task import LearnTask
from cxxnet_tpu.nnet.trainer import Trainer
from cxxnet_tpu.utils import checkpoint as ckpt
from cxxnet_tpu.utils import serializer

from . import faultinject as fi
from . import synth_mnist

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "tools"))
import ckpt_fsck  # noqa: E402


CONF = """
data = train
iter = mnist
    path_img = "{train_img}"
    path_label = "{train_lab}"
    shuffle = 1
iter = end
eval = test
iter = mnist
    path_img = "{test_img}"
    path_label = "{test_lab}"
iter = end

netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 32
  init_sigma = 0.01
layer[+1:sg1] = sigmoid:se1
layer[sg1->fc2] = fullc:fc2
  nhidden = 10
  init_sigma = 0.01
layer[+0] = softmax
netconfig=end

input_shape = 1,1,784
batch_size = 100

dev = cpu
save_model = 1
model_dir = {model_dir}
num_round = {num_round}
max_round = {num_round}
random_type = gaussian
eta = 0.2
momentum = 0.9
wd  = 0.0
metric = error
eval_train = 1
silent = 1
ckpt_fsync = 0
"""


@pytest.fixture(scope="module")
def mnist_data(tmp_path_factory):
    d = tmp_path_factory.mktemp("ckpt_mnist")
    return synth_mnist.make_dataset(str(d))


@pytest.fixture(scope="module")
def trained(tmp_path_factory, mnist_data):
    """One 3-round training run shared by the corruption scenarios; each
    test works on its own COPY of the models dir."""
    d = tmp_path_factory.mktemp("ckpt_base")
    conf = str(d / "train.conf")
    with open(conf, "w") as f:
        f.write(CONF.format(model_dir=str(d / "models"), num_round=3,
                            **mnist_data))
    task = LearnTask()
    task.run([conf])
    return {"dir": str(d), "conf": conf, "models": str(d / "models"),
            "err": task.net_trainer.metric.evals[0].get()}


def run_task(conf, *overrides):
    task = LearnTask()
    task.run([conf] + list(overrides))
    return task


def copy_models(trained, tmp_path):
    dst = str(tmp_path / "models")
    shutil.copytree(trained["models"], dst)
    return dst


def model(d, counter):
    return os.path.join(d, "%04d.model" % counter)


# ----------------------------------------------------------------------
# framing / serializer units
def test_footer_roundtrip_and_corruption_classes():
    payload = b"\x00\x00\x00\x00" + b"payload-bytes" * 7
    blob = ckpt.frame(payload)
    out, fmt = ckpt.split_footer(blob)
    assert out == payload and fmt == "v1"
    # legacy (unframed) bytes pass through
    out, fmt = ckpt.split_footer(payload)
    assert out == payload and fmt == "legacy"
    # truncation: header survives, footer gone -> corrupt, NOT legacy
    with pytest.raises(ckpt.CheckpointCorruptError):
        ckpt.split_footer(blob[: len(blob) // 2])
    # torn final block
    with pytest.raises(ckpt.CheckpointCorruptError):
        ckpt.split_footer(blob[:-1])
    # bit flip in the payload -> CRC mismatch
    flipped = bytearray(blob)
    flipped[len(ckpt.HEADER_MAGIC) + 3] ^= 0x01
    with pytest.raises(ckpt.CheckpointCorruptError, match="CRC"):
        ckpt.split_footer(bytes(flipped))
    # bit flip in the header magic -> length mismatch, still corrupt
    flipped = bytearray(blob)
    flipped[0] ^= 0x01
    with pytest.raises(ckpt.CheckpointCorruptError):
        ckpt.split_footer(bytes(flipped))


def test_serializer_rejects_short_and_corrupt_reads():
    w = serializer.Writer()
    w.write_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
    blob = w.getvalue()
    # truncated tensor payload raises EOFError, never returns short bytes
    with pytest.raises(EOFError):
        serializer.Reader(blob[:-5]).read_tensor()
    with pytest.raises(EOFError):
        serializer.Reader(b"\x01\x02").read_int32()
    ok = serializer.Reader(blob).read_tensor()
    assert ok.shape == (3, 4)
    # corrupt ndim (negative / absurd) fails loudly
    w2 = serializer.Writer()
    w2.write_int32(-3)
    with pytest.raises(ValueError, match="ndim"):
        serializer.Reader(w2.getvalue()).read_tensor()
    # absurd string length fails before allocating
    w3 = serializer.Writer()
    w3.write_uint64(1 << 60)
    with pytest.raises(ValueError, match="string"):
        serializer.Reader(w3.getvalue()).read_string()


def test_missing_state_section_returns_none():
    tr = Trainer()
    r = serializer.Reader(b"")
    assert tr.load_training_state(r) is None


# ----------------------------------------------------------------------
# atomic write: kill mid-write, rename failure, disk full
def test_atomic_write_rename_failure_retries(tmp_path, monkeypatch):
    path = str(tmp_path / "a.model")
    ckpt.write_checkpoint(path, b"old-contents")
    monkeypatch.setattr(ckpt.os, "replace",
                        fi.failing_once(os.replace))
    ckpt.write_checkpoint(path, b"new-contents", retries=2, base_delay=0.0)
    assert ckpt.read_verified(path)[0] == b"new-contents"
    assert not os.path.exists(path + ".tmp")


def test_atomic_write_hard_failure_keeps_old_file(tmp_path, monkeypatch):
    path = str(tmp_path / "a.model")
    ckpt.write_checkpoint(path, b"old-contents")
    monkeypatch.setattr(ckpt.os, "replace", fi.always_failing())
    with pytest.raises(OSError):
        ckpt.write_checkpoint(path, b"new-contents", retries=1,
                              base_delay=0.0)
    # the old file is intact and verified; no torn tmp left behind
    assert ckpt.read_verified(path)[0] == b"old-contents"
    assert not os.path.exists(path + ".tmp")


def test_disk_full_leaves_no_partial_file(tmp_path, monkeypatch):
    path = str(tmp_path / "a.model")
    monkeypatch.setattr(ckpt.os, "fsync", fi.always_failing())
    with pytest.raises(OSError):
        ckpt.write_checkpoint(path, b"doomed", retries=1, base_delay=0.0)
    assert not os.path.exists(path)
    assert not os.path.exists(path + ".tmp")


# ----------------------------------------------------------------------
# recovery scans
def test_resume_tolerates_numbering_gaps(tmp_path, trained):
    models = copy_models(trained, tmp_path)
    os.remove(model(models, 1))      # gap where the old scan stopped
    os.remove(model(models, 3))
    task = run_task(trained["conf"], "continue=1", "model_dir=%s" % models,
                    "num_round=3")
    assert task.start_counter == 4   # resumed from 0002, ran round 2
    assert os.path.exists(model(models, 3))


def test_resume_quarantines_truncated_newest(tmp_path, trained):
    models = copy_models(trained, tmp_path)
    fi.truncate(model(models, 3))
    task = run_task(trained["conf"], "continue=1", "model_dir=%s" % models,
                    "num_round=3")
    # fell back to 0002, re-ran round 2, rewrote a valid 0003
    assert os.path.exists(model(models, 3) + ".corrupt")
    assert ckpt_fsck.inspect_file(model(models, 3))["status"] == "ok"
    assert task.start_counter == 4


def test_resume_quarantines_bit_flipped_newest(tmp_path, trained):
    models = copy_models(trained, tmp_path)
    fi.bit_flip(model(models, 3))
    task = run_task(trained["conf"], "continue=1", "model_dir=%s" % models,
                    "num_round=3")
    assert os.path.exists(model(models, 3) + ".corrupt")
    assert task.start_counter == 4
    err = task.net_trainer.metric.evals[0].get()
    assert err == trained["err"]     # identical to the uninterrupted run


def test_resume_all_corrupt_fails_loudly(tmp_path, trained):
    models = copy_models(trained, tmp_path)
    for c in range(4):
        fi.bit_flip(model(models, c))
    with pytest.raises(RuntimeError, match="Cannot find models"):
        run_task(trained["conf"], "continue=1", "model_dir=%s" % models,
                 "num_round=3")
    # every candidate was quarantined, none was loaded as garbage
    for c in range(4):
        assert not os.path.exists(model(models, c))
        assert os.path.exists(model(models, c) + ".corrupt")


def test_config_mismatch_aborts_without_quarantine(tmp_path, trained):
    """A CRC-verified checkpoint that fails to parse is a config mismatch,
    not corruption: resume must abort loudly and leave the file alone
    (quarantining healthy checkpoints would destroy the run's history)."""
    models = copy_models(trained, tmp_path)
    conf2 = str(tmp_path / "bigger.conf")
    text = open(trained["conf"]).read().replace(
        "layer[+1:sg1] = sigmoid:se1",
        "layer[+1:sg1] = sigmoid:se1\nlayer[+1:fcX] = fullc:fcX\n"
        "  nhidden = 24\n  init_sigma = 0.01")
    open(conf2, "w").write(text)
    with pytest.raises(RuntimeError, match="CRC verified.*mismatch"):
        run_task(conf2, "continue=1", "model_dir=%s" % models,
                 "num_round=3")
    for c in range(4):   # every checkpoint untouched, nothing quarantined
        assert os.path.exists(model(models, c))
        assert not os.path.exists(model(models, c) + ".corrupt")
    # the original config still resumes fine
    task = run_task(trained["conf"], "continue=1", "model_dir=%s" % models,
                    "num_round=4")
    assert task.start_counter == 5


def test_stale_tmp_ignored_and_collected(tmp_path, trained):
    models = copy_models(trained, tmp_path)
    stale = fi.make_stale_tmp(models)
    task = run_task(trained["conf"], "continue=1", "model_dir=%s" % models,
                    "num_round=4")
    assert task.start_counter == 5
    assert not os.path.exists(stale)          # GC'd at the next save
    assert os.path.exists(model(models, 4))


def test_legacy_footerless_checkpoint_still_loads(tmp_path, trained):
    models = copy_models(trained, tmp_path)
    fi.strip_framing(model(models, 3))        # seed-format file, no footer
    assert ckpt_fsck.inspect_file(model(models, 3))["status"] == "legacy"
    task = run_task(trained["conf"], "continue=1", "model_dir=%s" % models,
                    "num_round=4")
    assert task.start_counter == 5
    assert ckpt_fsck.inspect_file(model(models, 4))["status"] == "ok"


# ----------------------------------------------------------------------
# schedules, retention
def test_save_period_saves_round_zero_and_final(tmp_path, mnist_data):
    conf = str(tmp_path / "t.conf")
    with open(conf, "w") as f:
        f.write(CONF.format(model_dir=str(tmp_path / "models"), num_round=4,
                            **mnist_data))
    run_task(conf, "save_model=3")
    models = str(tmp_path / "models")
    have = sorted(c for c, _ in ckpt.scan_checkpoints(models))
    # counter % 3 == 0 -> 0000, 0003; final round always saved -> 0004.
    # (the reference's off-by-one saved rounds 2, 5, ... and never round 0)
    assert have == [0, 3, 4]


def test_max_round_capped_session_saves_final_round(tmp_path, mnist_data):
    """A session ended by the max_round per-invocation cap (not num_round)
    must still checkpoint its last round despite save_period gaps."""
    conf = str(tmp_path / "t.conf")
    with open(conf, "w") as f:
        f.write(CONF.format(model_dir=str(tmp_path / "models"),
                            num_round=50, **mnist_data))
    run_task(conf, "save_model=5", "max_round=2")
    have = sorted(c for c, _ in ckpt.scan_checkpoints(
        str(tmp_path / "models")))
    assert have == [0, 2]   # initial + the cap's final round (forced)


def test_retry_io_skips_permanent_errors():
    import errno
    calls = {"n": 0}

    def missing():
        calls["n"] += 1
        raise FileNotFoundError(errno.ENOENT, "no such file")

    with pytest.raises(FileNotFoundError):
        ckpt.retry_io(missing, retries=3, base_delay=0.0)
    assert calls["n"] == 1          # permanent error: never retried

    calls["n"] = 0

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError(errno.EIO, "injected transient error")
        return "ok"

    assert ckpt.retry_io(flaky, retries=3, base_delay=0.0) == "ok"
    assert calls["n"] == 3          # transient error: retried


def test_retention_policy(tmp_path, mnist_data):
    conf = str(tmp_path / "t.conf")
    with open(conf, "w") as f:
        f.write(CONF.format(model_dir=str(tmp_path / "models"), num_round=6,
                            **mnist_data))
    run_task(conf, "ckpt_keep_last=2", "ckpt_keep_every=3")
    have = sorted(c for c, _ in ckpt.scan_checkpoints(
        str(tmp_path / "models")))
    # newest 2 (0005, 0006) + every 3rd anchor (0000, 0003, 0006)
    assert have == [0, 3, 5, 6]


# ----------------------------------------------------------------------
# preemption: SIGTERM mid-round -> emergency checkpoint -> exact resume
def test_sigterm_emergency_checkpoint_exact_resume(tmp_path, mnist_data,
                                                   monkeypatch):
    da, db = tmp_path / "a", tmp_path / "b"
    da.mkdir(), db.mkdir()
    confs = {}
    for name, d in (("a", da), ("b", db)):
        confs[name] = str(d / "t.conf")
        with open(confs[name], "w") as f:
            f.write(CONF.format(model_dir=str(d / "models"), num_round=3,
                                **mnist_data))
    task_a = run_task(confs["a"])                      # uninterrupted
    # interrupted: SIGTERM after 9 updates = 3 batches into round 1
    monkeypatch.setattr(Trainer, "update",
                        fi.killing_method(Trainer.update, n=9))
    task_b = run_task(confs["b"])
    monkeypatch.undo()
    emergency = str(db / "models" / ckpt.EMERGENCY_NAME)
    assert os.path.exists(emergency)
    assert task_b.start_counter == 2                   # stopped mid round 1
    st = ckpt.peek_state(ckpt.read_verified(emergency)[0])
    assert (st["start_counter"], st["batches_done"]) == (2, 3)
    # resume completes rounds 1-2 from the emergency cursor
    task_c = run_task(confs["b"], "continue=1")
    assert task_c.start_counter == 4
    assert not os.path.exists(emergency)   # superseded by numbered save
    # bit-for-bit: metrics, rng stream, and every weight match the
    # uninterrupted run exactly (CPU backend)
    assert (task_c.net_trainer.metric.evals[0].get()
            == task_a.net_trainer.metric.evals[0].get())
    assert task_c.net_trainer._rng_counter == task_a.net_trainer._rng_counter
    pa = task_a.net_trainer.canonical_params()
    pc = task_c.net_trainer.canonical_params()
    for la, lc in zip(pa, pc):
        assert set(la) == set(lc)
        for k in la:
            assert np.array_equal(np.asarray(la[k]), np.asarray(lc[k])), k


def test_sigterm_mid_accumulation_restores_grad_accum(tmp_path, mnist_data,
                                                      monkeypatch):
    """update_period=2 killed after an ODD update: the in-flight gradient
    accumulator must survive the checkpoint for exact resume."""
    da, db = tmp_path / "a", tmp_path / "b"
    da.mkdir(), db.mkdir()
    confs = {}
    for name, d in (("a", da), ("b", db)):
        confs[name] = str(d / "t.conf")
        with open(confs[name], "w") as f:
            f.write(CONF.format(model_dir=str(d / "models"), num_round=2,
                                **mnist_data))
    task_a = run_task(confs["a"], "update_period=2")
    monkeypatch.setattr(Trainer, "update",
                        fi.killing_method(Trainer.update, n=9))
    run_task(confs["b"], "update_period=2")
    monkeypatch.undo()
    task_c = run_task(confs["b"], "continue=1", "update_period=2")
    assert task_c.net_trainer.epoch_counter == task_a.net_trainer.epoch_counter
    pa = task_a.net_trainer.canonical_params()
    pc = task_c.net_trainer.canonical_params()
    for la, lc in zip(pa, pc):
        for k in la:
            assert np.array_equal(np.asarray(la[k]), np.asarray(lc[k])), k


# ----------------------------------------------------------------------
# telemetry + fsck integration
def test_ckpt_telemetry_events(tmp_path, trained):
    from cxxnet_tpu.utils import telemetry
    models = copy_models(trained, tmp_path)
    fi.truncate(model(models, 3))
    log = str(tmp_path / "run.jsonl")
    try:
        run_task(trained["conf"], "continue=1", "model_dir=%s" % models,
                 "num_round=3", "telemetry_log=%s" % log)
    finally:
        telemetry.disable()
    events = [json.loads(l) for l in open(log) if l.strip()]
    by_ev = {}
    for e in events:
        by_ev.setdefault(e["ev"], []).append(e)
    assert any(e["path"].endswith("0003.model")
               for e in by_ev["ckpt_corrupt"])
    assert any(e["path"].endswith("0002.model")
               for e in by_ev["ckpt_restore"])
    assert any(e["path"].endswith("0003.model") and e["bytes"] > 0
               for e in by_ev["ckpt_save"])


def test_fsck_flags_every_injected_corruption(tmp_path, trained, capsys):
    models = copy_models(trained, tmp_path)
    fi.truncate(model(models, 1))
    fi.bit_flip(model(models, 2))
    fi.make_stale_tmp(models)
    assert ckpt_fsck.main([models]) == 1
    out = capsys.readouterr().out
    assert out.count("CORRUPT") == 2 and "STALE" in out
    rep = {r["path"]: r for r in
           (ckpt_fsck.inspect_file(model(models, c)) for c in range(4))}
    statuses = [rep[model(models, c)]["status"] for c in range(4)]
    assert statuses == ["ok", "corrupt", "corrupt", "ok"]
    # clean dir passes (exit 0) and reports the training cursor
    assert ckpt_fsck.main([trained["models"]]) == 0
    assert ckpt_fsck.selftest() == 0
