"""The embeddable numpy API (cxxnet_tpu.api): DataIter / Net / train —
reference wrapper surface wrapper/cxxnet.py:64-307."""

import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from cxxnet_tpu import api
from tests.synth_mnist import make_dataset

NET_CFG = """
netconfig = start
layer[+1:fc1] = fullc:fc1
  nhidden = 32
  init_sigma = 0.05
layer[+1] = relu
layer[+1:fc2] = fullc:fc2
  nhidden = 10
  init_sigma = 0.05
layer[+0] = softmax
netconfig = end
input_shape = 1,1,784
batch_size = 25
eta = 0.1
momentum = 0.9
metric = error
"""


@pytest.fixture(scope="module")
def mnist_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("mnist")
    make_dataset(str(d), n_train=200, n_test=100)
    return str(d)


def _iter_cfg(d, split="train-images-idx3-ubyte.gz",
              labels="train-labels-idx1-ubyte.gz"):
    return """
iter = mnist
  path_img = "%s/%s"
  path_label = "%s/%s"
  batch_size = 25
iter = end
""" % (d, split, d, labels)


class TestDataIter:
    def test_iterate(self, mnist_dir):
        it = api.DataIter(_iter_cfg(mnist_dir))
        n = 0
        while it.next():
            data, label = it.get_data(), it.get_label()
            assert data.shape == (25, 1, 1, 784)
            assert label.shape == (25, 1)
            n += 1
        assert n == 8
        it.before_first()
        assert it.next()

    def test_check_valid_before_next(self, mnist_dir):
        it = api.DataIter(_iter_cfg(mnist_dir))
        with pytest.raises(AssertionError):
            it.get_data()


class TestNet:
    def test_train_memorize_and_predict(self, mnist_dir):
        it = api.DataIter(_iter_cfg(mnist_dir))
        net = api.Net(dev="cpu", cfg=NET_CFG)
        net.init_model()
        for r in range(12):
            net.start_round(r)
            it.before_first()
            while it.next():
                net.update(it)
        ev = net.evaluate(api.DataIter(_iter_cfg(mnist_dir)), "train")
        err = float(ev.split("train-error:")[1])
        assert err < 0.1, ev

        it.before_first()
        it.next()
        pred = net.predict(it)
        assert pred.shape == (25,)
        labels = it.get_label()[:, 0]
        assert (pred == labels).mean() > 0.9

    def test_update_raw_numpy(self):
        rs = np.random.RandomState(0)
        x = rs.rand(25, 784).astype(np.float32)
        y = rs.randint(0, 10, 25).astype(np.float32)
        net = api.Net(dev="cpu", cfg=NET_CFG)
        net.init_model()
        for _ in range(150):
            net.update(x, y)
        pred = net.predict(x)
        assert (pred == y).mean() > 0.9, "should memorize one fixed batch"

    def test_extract_and_weights(self):
        net = api.Net(dev="cpu", cfg=NET_CFG)
        net.init_model()
        x = np.random.RandomState(1).rand(25, 784).astype(np.float32)
        feat = net.extract(x, "fc1")
        assert feat.reshape(25, -1).shape == (25, 32)
        top = net.extract(x, "top[-1]")
        np.testing.assert_allclose(top.reshape(25, -1).sum(-1),
                                   np.ones(25), rtol=1e-5)
        w = net.get_weight("fc1", "wmat")
        assert w.shape == (32, 784)
        net.set_weight(np.zeros_like(w), "fc1", "wmat")
        assert np.all(net.get_weight("fc1", "wmat") == 0)
        feat0 = net.extract(x, "fc1")
        assert np.all(feat0 == 0)

    def test_save_load_roundtrip(self, tmp_path):
        net = api.Net(dev="cpu", cfg=NET_CFG)
        net.init_model()
        x = np.random.RandomState(2).rand(25, 784).astype(np.float32)
        y = np.zeros(25, np.float32)
        net.update(x, y)
        p1 = net.extract(x, "top[-1]")
        path = str(tmp_path / "m.model")
        net.save_model(path)
        net2 = api.Net(dev="cpu", cfg="")
        net2.load_model(path)
        p2 = net2.extract(x, "top[-1]")
        np.testing.assert_allclose(np.asarray(p1), np.asarray(p2),
                                   rtol=1e-5, atol=1e-6)


def test_train_driver(mnist_dir):
    it = api.DataIter(_iter_cfg(mnist_dir))
    ev = api.DataIter(_iter_cfg(mnist_dir))
    net = api.train(NET_CFG, it, 6, {"eta": "0.2"}, eval_data=ev, dev="cpu")
    s = net.evaluate(ev, "final")
    assert float(s.split("final-error:")[1]) < 0.2, s


def test_optimizer_state_checkpointed(tmp_path):
    """Resume from a checkpoint must reproduce uninterrupted training
    bitwise (the reference dropped momentum on resume,
    nnet_impl-inl.hpp:82-87 — we checkpoint the optimizer too)."""
    rs = np.random.RandomState(5)
    x = rs.rand(25, 784).astype(np.float32)
    y = rs.randint(0, 10, 25).astype(np.float32)
    cfg = NET_CFG + "momentum = 0.9\n"

    # uninterrupted: 8 updates
    ref = api.Net(dev="cpu", cfg=cfg)
    ref.init_model()
    for _ in range(8):
        ref.update(x, y)

    # interrupted after 4, saved, resumed in a fresh Net
    a = api.Net(dev="cpu", cfg=cfg)
    a.init_model()
    for _ in range(4):
        a.update(x, y)
    path = str(tmp_path / "mid.model")
    a.save_model(path)
    b = api.Net(dev="cpu", cfg=cfg)
    b.load_model(path)
    # momentum restored, not re-zeroed
    m = b.net_.opt_state[0]["wmat"]["m"]
    assert float(np.abs(np.asarray(m)).max()) > 0
    for _ in range(4):
        b.update(x, y)

    for p_ref, p_b in zip(ref.net_.params, b.net_.params):
        for key in p_ref:
            np.testing.assert_array_equal(np.asarray(p_ref[key]),
                                          np.asarray(p_b[key]))


def test_old_format_model_still_loads(tmp_path):
    """Files without the optimizer section (or foreign trailing data) load
    with fresh optimizer state."""
    net = api.Net(dev="cpu", cfg=NET_CFG)
    net.init_model()
    x = np.random.RandomState(6).rand(25, 784).astype(np.float32)
    net.update(x, np.zeros(25, np.float32))
    path = str(tmp_path / "m.model")
    net.save_model(path)
    # strip the integrity framing and the optimizer section to emulate a
    # legacy (seed-era) file: no footer, nothing after the model blob
    from cxxnet_tpu.utils import checkpoint as ckpt
    payload, _ = ckpt.split_footer(open(path, "rb").read())
    cut = payload.rindex(b"CXNOPT01")
    open(path, "wb").write(payload[:cut])
    net2 = api.Net(dev="cpu", cfg="")
    net2.load_model(path)
    p1 = net.extract(x, "top[-1]")
    p2 = net2.extract(x, "top[-1]")
    np.testing.assert_allclose(np.asarray(p1), np.asarray(p2),
                               rtol=1e-5, atol=1e-6)
