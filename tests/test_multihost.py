"""Real multi-process distributed training: 2 worker processes x 4 virtual
CPU devices = one 8-device global mesh over the Gloo CPU backend — the
closest this sandbox gets to multi-host DCN. Validates init_distributed,
global-mesh trainer steps, and cross-process replica consistency (the
reference's dist-PS role, SURVEY.md §2.9 row 2)."""

import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent('''
import os, sys
# JAX_PLATFORMS / XLA_FLAGS come from the parent via virtual_cpu_env(4)
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, %(repo)r)
from cxxnet_tpu.parallel import init_distributed
rank = int(sys.argv[1])
init_distributed(%(coord)r, 2, rank)
assert jax.process_count() == 2
assert len(jax.devices()) == 8 and len(jax.local_devices()) == 4

import numpy as np
import jax.numpy as jnp
from jax.experimental import multihost_utils
from cxxnet_tpu.nnet.trainer import Trainer
from cxxnet_tpu.utils.config import parse_config_string
from cxxnet_tpu.io.data import DataBatch

conf = """
netconfig = start
layer[+1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.05
layer[+1] = relu
layer[+1] = fullc:fc2
  nhidden = 10
  init_sigma = 0.05
layer[+0] = softmax
netconfig = end
input_shape = 1,1,32
batch_size = 16
eta = 0.1
dev = tpu:0-7
seed = 3
"""
tr = Trainer()
for k, v in parse_config_string(conf):
    tr.set_param(k, v)
tr.init_model()
assert tr.mesh is not None and tr.mesh.devices.size == 8

rs = np.random.RandomState(0)  # identical global batch on both hosts
b = DataBatch()
b.data = rs.rand(16, 1, 1, 32).astype(np.float32)
b.label = rs.randint(0, 10, (16, 1)).astype(np.float32)
b.batch_size = 16
for _ in range(5):
    tr.update(b)

# replica consistency ACROSS processes: every host's local shard of the
# (replicated) weights must be identical — host-side allgather of numpy
local = np.asarray(tr.params[0]["wmat"].addressable_shards[0].data)
gathered = multihost_utils.process_allgather(local)
assert gathered.shape[0] == 2
np.testing.assert_array_equal(gathered[0], gathered[1])
assert np.isfinite(gathered).all()
print("RANK%%d_OK" %% rank)

# multi-process fetch paths: predict gathers the mesh-sharded forward
# output to every host; save_model serializes ZeRO-sharded (update_on_server)
# optimizer state through parallel.fetch_global
tr2 = Trainer()
for k, v in parse_config_string(conf + "update_on_server = 1\\n"):
    tr2.set_param(k, v)
tr2.init_model()
for _ in range(2):
    tr2.update(b)
pred = tr2.predict(b)
assert pred.shape == (16,)
from cxxnet_tpu.utils import serializer
w = serializer.Writer()
tr2.save_model(w)
blob = w.getvalue()
assert len(blob) > 1000
gathered_pred = multihost_utils.process_allgather(pred)
np.testing.assert_array_equal(gathered_pred[0], gathered_pred[1])
print("RANK%%d_SAVE_OK" %% rank)

# per-host LOCAL-shard feeding (dist_num_worker-sharded corpora): each
# host supplies only its 8-row slice of the 16-row global batch;
# make_array_from_process_local_data must assemble the same global batch,
# so training matches the identical-global-batch run exactly
tr3 = Trainer()
for k, v in parse_config_string(conf):
    tr3.set_param(k, v)
tr3.init_model()
lo = rank * 8
b3 = DataBatch()
b3.data = b.data[lo:lo + 8]
b3.label = b.label[lo:lo + 8]
b3.batch_size = 16
for _ in range(5):
    tr3.update(b3)
w_full = np.asarray(tr.params[0]["wmat"].addressable_shards[0].data)
w_shard = np.asarray(tr3.params[0]["wmat"].addressable_shards[0].data)
np.testing.assert_allclose(w_shard, w_full, rtol=1e-6, atol=1e-7)
pred3 = tr3.predict(b3)          # shard-fed predict returns GLOBAL rows
assert pred3.shape == (16,)
print("RANK%%d_SHARD_OK" %% rank)

# fsdp across processes: params shard over the data axis spanning BOTH
# hosts (1/8 addressable), numerics match the replicated run, and
# save_model gathers the cross-process shards through fetch_global
tr5 = Trainer()
for k, v in parse_config_string(conf + "fsdp = 1\\n"):
    tr5.set_param(k, v)
tr5.init_model()
w5 = tr5.params[0]["wmat"]
assert np.asarray(w5.addressable_shards[0].data).size * 8 == w5.size, \
    w5.sharding
for _ in range(5):
    tr5.update(b)
w5 = tr5.params[0]["wmat"]
assert np.asarray(w5.addressable_shards[0].data).size * 8 == w5.size, \
    w5.sharding
from cxxnet_tpu.parallel import fetch_global
w5_full = np.asarray(fetch_global(w5))
np.testing.assert_allclose(w5_full[:, :], np.asarray(
    fetch_global(tr.params[0]["wmat"])), rtol=1e-6, atol=1e-7)
w = serializer.Writer()
tr5.save_model(w)
assert len(w.getvalue()) > 1000
print("RANK%%d_FSDP_OK" %% rank)

# hybrid DCN x ICI mesh: with model_parallel the trainer auto-builds the
# mesh so TP pairs stay INSIDE a process (ICI) while the data axis spans
# the two processes (DCN) — parallel.create_hybrid_mesh wired end-to-end
tr4 = Trainer()
for k, v in parse_config_string(conf + "model_parallel = 2\\n"):
    tr4.set_param(k, v)
tr4.init_model()
assert tr4.mesh.axis_names == ("data", "model")
assert tr4.mesh.shape["data"] == 4 and tr4.mesh.shape["model"] == 2
mdev = tr4.mesh.devices          # (data=4, model=2) device array
for i in range(4):
    row_procs = {d.process_index for d in mdev[i]}
    assert len(row_procs) == 1, (
        "model-axis pair %%d crosses processes: %%r" %% (i, row_procs))
for _ in range(5):
    tr4.update(b)
w4 = np.asarray(tr4.params[0]["wmat"].addressable_shards[0].data)
assert np.isfinite(w4).all()
# eval metrics must align labels with the hybrid mesh's data-axis DEVICE
# order (global arrays), not process-allgather order — feed per-host
# shards so the global-gather branch actually runs
class _OneBatchIter:
    def __init__(self, b): self.b = b; self.done = False
    def before_first(self): self.done = False
    def next(self):
        if self.done: return False
        self.done = True; return True
    def value(self): return self.b
b4 = DataBatch()
b4.data = b.data[lo:lo + 8]
b4.label = b.label[lo:lo + 8]
b4.batch_size = 16
tr4.metric.add_metric("error", "label")
tr4.eval_nodes = [tr4.net_cfg.param.num_nodes - 1]
s = tr4.evaluate(_OneBatchIter(b4), "hybrid")
assert "hybrid-error" in s
# cross-check: the aligned metric equals the error computed host-side on
# the full global batch
pred4 = tr4.predict(b4)
err_ref = float(np.mean(pred4 != b.label[:, 0]))
err_got = float(s.split("hybrid-error:")[1].split()[0])
assert abs(err_got - err_ref) < 1e-6, (err_got, err_ref)
print("RANK%%d_HYBRID_OK" %% rank)

# pipeline parallelism across the 2-process mesh: mesh (data=2, pipe=4)
# puts each pipe group on one process's 4 local devices (ppermute hops
# ride the intra-process "ICI"; the data all-reduce crosses "DCN"), and
# stage params pack sharded by pipe rank as in single-process runs
tr5 = Trainer()
for k, v in parse_config_string(conf + "pipeline_parallel = 4\\n"):
    tr5.set_param(k, v)
tr5.init_model()
assert tr5.mesh.axis_names == ("data", "pipe")
assert tr5.mesh.shape["data"] == 2 and tr5.mesh.shape["pipe"] == 4
for i in range(tr5.mesh.shape["data"]):
    row_procs = {d.process_index for d in tr5.mesh.devices[i]}
    assert len(row_procs) == 1, (
        "pipe group %%d crosses processes: %%r" %% (i, row_procs))
for _ in range(3):
    tr5.update(b)
canon5 = tr5.canonical_params()
w5 = np.asarray(canon5[0]["wmat"])
gathered5 = multihost_utils.process_allgather(w5)
np.testing.assert_array_equal(gathered5[0], gathered5[1])
assert np.isfinite(gathered5).all()
pred5 = tr5.predict(b)
assert pred5.shape == (16,)
print("RANK%%d_PP_OK" %% rank)
''')


_CPU_BACKEND = os.environ.get("JAX_PLATFORMS", "").startswith("cpu")
_CPU_MULTIPROC_XFAIL = pytest.mark.xfail(
    _CPU_BACKEND, strict=True,
    reason="pre-existing (PR <= 8): this jax build's CPU backend "
           "refuses cross-process device_put ('Multiprocess "
           "computations aren't implemented on the CPU backend') — "
           "the 2-process Gloo tunnel dies in _shard_batch (passes on "
           "a real multi-host backend); ROADMAP item 7 owns the "
           "revival")


@_CPU_MULTIPROC_XFAIL
def test_two_process_distributed_training(tmp_path):
    prog = WORKER % {"repo": REPO, "coord": "localhost:45683"}
    from cxxnet_tpu.parallel import virtual_cpu_env
    env = virtual_cpu_env(4)
    procs = [subprocess.Popen(
        [sys.executable, "-c", prog, str(r)], stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, env=env) for r in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, "rank %d failed:\n%s" % (r, out[-2000:])
        assert ("RANK%d_OK" % r) in out
        assert ("RANK%d_SAVE_OK" % r) in out
        assert ("RANK%d_SHARD_OK" % r) in out
        assert ("RANK%d_FSDP_OK" % r) in out
        assert ("RANK%d_HYBRID_OK" % r) in out
        assert ("RANK%d_PP_OK" % r) in out


FAULT_WORKER = r'''
import os, sys
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, REPO)
from cxxnet_tpu.parallel import init_distributed
rank = int(sys.argv[1])
phase = sys.argv[2]          # ref | crash | resume
coord = sys.argv[3]
workdir = sys.argv[4]
init_distributed(coord, 2, rank)

import numpy as np
from cxxnet_tpu.nnet.trainer import Trainer
from cxxnet_tpu.utils.config import parse_config_string
from cxxnet_tpu.utils import serializer
from cxxnet_tpu.io.data import DataBatch

conf = """
netconfig = start
layer[+1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.05
layer[+1] = relu
layer[+1] = fullc:fc2
  nhidden = 10
  init_sigma = 0.05
layer[+0] = softmax
netconfig = end
input_shape = 1,1,32
batch_size = 16
eta = 0.1
momentum = 0.9
update_on_server = 1
dev = tpu:0-7
seed = 3
"""

def make_trainer():
    tr = Trainer()
    for k, v in parse_config_string(conf):
        tr.set_param(k, v)
    return tr

rs = np.random.RandomState(0)
batches = []
for _ in range(6):
    b = DataBatch()
    b.data = rs.rand(16, 1, 1, 32).astype(np.float32)
    b.label = rs.randint(0, 10, (16, 1)).astype(np.float32)
    b.batch_size = 16
    batches.append(b)

def save(tr, path):
    # collective: every rank calls save_model; rank 0 writes the file
    w = serializer.Writer()
    tr.save_model(w)
    if rank == 0:
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(w.getvalue())
        os.replace(tmp, path)

if phase == "ref":
    tr = make_trainer(); tr.init_model()
    for b in batches:
        tr.update(b)
    save(tr, os.path.join(workdir, "ref.model"))
    print("RANK%d_REF_DONE" % rank, flush=True)
elif phase == "crash":
    tr = make_trainer(); tr.init_model()
    for b in batches[:3]:
        tr.update(b)
    save(tr, os.path.join(workdir, "ckpt.model"))
    print("RANK%d_CKPT_WRITTEN" % rank, flush=True)
    # keep training the next round until the driver SIGKILLs us mid-step
    i = 0
    while True:
        tr.update(batches[3 + i % 3])
        i += 1
elif phase == "resume":
    # the reference's recovery story: restart with continue=1 and resume
    # from the newest checkpoint (src/cxxnet_main.cpp:109-118,135-157)
    tr = make_trainer()
    with open(os.path.join(workdir, "ckpt.model"), "rb") as f:
        tr.load_model(serializer.Reader(f.read()))
    assert tr.epoch_counter == 3
    for b in batches[3:]:
        tr.update(b)
    save(tr, os.path.join(workdir, "resumed.model"))
    print("RANK%d_RESUME_DONE" % rank, flush=True)
'''


@_CPU_MULTIPROC_XFAIL
def test_kill_and_resume_bitwise(tmp_path):
    """Kill a worker mid-round; relaunch; continuation from the checkpoint
    (incl. ZeRO-sharded optimizer state) is BITWISE identical to the
    uninterrupted 2-process run."""
    import signal
    import time
    from cxxnet_tpu.parallel import virtual_cpu_env
    env = virtual_cpu_env(4)
    wd = str(tmp_path)
    prog = "REPO = %r\n" % REPO + FAULT_WORKER

    def spawn(phase, port):
        return [subprocess.Popen(
            [sys.executable, "-c", prog, str(r), phase,
             "localhost:%d" % port, wd],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env) for r in range(2)]

    # uninterrupted reference run
    procs = spawn("ref", 45701)
    for r, p in enumerate(procs):
        out, _ = p.communicate(timeout=240)
        assert p.returncode == 0, "ref rank %d:\n%s" % (r, out[-2000:])

    # crash run: wait for the checkpoint, then SIGKILL rank 1 mid-round,
    # then rank 0 (the job is dead once a worker is gone — the reference
    # exits via utils::Error too; recovery is restart + continue)
    procs = spawn("crash", 45703)
    ckpt = os.path.join(wd, "ckpt.model")
    deadline = time.time() + 240
    while not os.path.exists(ckpt) and time.time() < deadline:
        time.sleep(0.5)
        assert all(p.poll() is None for p in procs), [
            p.communicate()[0][-800:] for p in procs if p.poll() is not None]
    assert os.path.exists(ckpt), "checkpoint never appeared"
    time.sleep(1.0)          # let the next round get going
    procs[1].send_signal(signal.SIGKILL)
    time.sleep(0.5)
    procs[0].send_signal(signal.SIGKILL)
    for p in procs:
        p.wait(timeout=60)

    # relaunch with the checkpoint
    procs = spawn("resume", 45705)
    for r, p in enumerate(procs):
        out, _ = p.communicate(timeout=240)
        assert p.returncode == 0, "resume rank %d:\n%s" % (r, out[-2000:])
        assert ("RANK%d_RESUME_DONE" % r) in out

    with open(os.path.join(wd, "ref.model"), "rb") as f:
        ref = f.read()
    with open(os.path.join(wd, "resumed.model"), "rb") as f:
        resumed = f.read()
    assert ref == resumed, "resumed run diverged from uninterrupted run"
