"""Real multi-process distributed training: 2 worker processes x 4 virtual
CPU devices = one 8-device global mesh over the Gloo CPU backend — the
closest this sandbox gets to multi-host DCN. Validates init_distributed,
global-mesh trainer steps, and cross-process replica consistency (the
reference's dist-PS role, SURVEY.md §2.9 row 2)."""

import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent('''
import os, sys
# JAX_PLATFORMS / XLA_FLAGS come from the parent via virtual_cpu_env(4)
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, %(repo)r)
from cxxnet_tpu.parallel import init_distributed
rank = int(sys.argv[1])
init_distributed(%(coord)r, 2, rank)
assert jax.process_count() == 2
assert len(jax.devices()) == 8 and len(jax.local_devices()) == 4

import numpy as np
import jax.numpy as jnp
from jax.experimental import multihost_utils
from cxxnet_tpu.nnet.trainer import Trainer
from cxxnet_tpu.utils.config import parse_config_string
from cxxnet_tpu.io.data import DataBatch

conf = """
netconfig = start
layer[+1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.05
layer[+1] = relu
layer[+1] = fullc:fc2
  nhidden = 10
  init_sigma = 0.05
layer[+0] = softmax
netconfig = end
input_shape = 1,1,32
batch_size = 16
eta = 0.1
dev = tpu:0-7
seed = 3
"""
tr = Trainer()
for k, v in parse_config_string(conf):
    tr.set_param(k, v)
tr.init_model()
assert tr.mesh is not None and tr.mesh.devices.size == 8

rs = np.random.RandomState(0)  # identical global batch on both hosts
b = DataBatch()
b.data = rs.rand(16, 1, 1, 32).astype(np.float32)
b.label = rs.randint(0, 10, (16, 1)).astype(np.float32)
b.batch_size = 16
for _ in range(5):
    tr.update(b)

# replica consistency ACROSS processes: every host's local shard of the
# (replicated) weights must be identical — host-side allgather of numpy
local = np.asarray(tr.params[0]["wmat"].addressable_shards[0].data)
gathered = multihost_utils.process_allgather(local)
assert gathered.shape[0] == 2
np.testing.assert_array_equal(gathered[0], gathered[1])
assert np.isfinite(gathered).all()
print("RANK%%d_OK" %% rank)

# multi-process fetch paths: predict gathers the mesh-sharded forward
# output to every host; save_model serializes ZeRO-sharded (update_on_server)
# optimizer state through parallel.fetch_global
tr2 = Trainer()
for k, v in parse_config_string(conf + "update_on_server = 1\\n"):
    tr2.set_param(k, v)
tr2.init_model()
for _ in range(2):
    tr2.update(b)
pred = tr2.predict(b)
assert pred.shape == (16,)
from cxxnet_tpu.utils import serializer
w = serializer.Writer()
tr2.save_model(w)
blob = w.getvalue()
assert len(blob) > 1000
gathered_pred = multihost_utils.process_allgather(pred)
np.testing.assert_array_equal(gathered_pred[0], gathered_pred[1])
print("RANK%%d_SAVE_OK" %% rank)

# per-host LOCAL-shard feeding (dist_num_worker-sharded corpora): each
# host supplies only its 8-row slice of the 16-row global batch;
# make_array_from_process_local_data must assemble the same global batch,
# so training matches the identical-global-batch run exactly
tr3 = Trainer()
for k, v in parse_config_string(conf):
    tr3.set_param(k, v)
tr3.init_model()
lo = rank * 8
b3 = DataBatch()
b3.data = b.data[lo:lo + 8]
b3.label = b.label[lo:lo + 8]
b3.batch_size = 16
for _ in range(5):
    tr3.update(b3)
w_full = np.asarray(tr.params[0]["wmat"].addressable_shards[0].data)
w_shard = np.asarray(tr3.params[0]["wmat"].addressable_shards[0].data)
np.testing.assert_allclose(w_shard, w_full, rtol=1e-6, atol=1e-7)
pred3 = tr3.predict(b3)          # shard-fed predict returns GLOBAL rows
assert pred3.shape == (16,)
print("RANK%%d_SHARD_OK" %% rank)
''')


def test_two_process_distributed_training(tmp_path):
    prog = WORKER % {"repo": REPO, "coord": "localhost:45683"}
    from cxxnet_tpu.parallel import virtual_cpu_env
    env = virtual_cpu_env(4)
    procs = [subprocess.Popen(
        [sys.executable, "-c", prog, str(r)], stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, env=env) for r in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, "rank %d failed:\n%s" % (r, out[-2000:])
        assert ("RANK%d_OK" % r) in out
        assert ("RANK%d_SAVE_OK" % r) in out
        assert ("RANK%d_SHARD_OK" % r) in out
