"""Gradient-accumulation equivalence: update_period=k on 1/k-size batches
must reproduce the single large-batch update exactly (the reference's
need_sync/need_update contract, src/nnet/nnet_impl-inl.hpp:146-185, with
loss pre-scaled by 1/(batch*update_period))."""

import numpy as np
import jax

from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.nnet.trainer import Trainer
from cxxnet_tpu.utils.config import parse_config_string

CONF = """
netconfig=start
layer[0->c1] = conv:c1
  kernel_size = 3
  pad = 1
  nchannel = 4
layer[c1->r1] = relu
layer[r1->fl] = flatten
layer[fl->out] = fullc:head
  nhidden = 5
layer[+0] = softmax
netconfig=end
random_type = xavier
metric = error
input_shape = 3,6,6
dev = cpu
eta = 0.1
momentum = 0.9
wd = 0.0001
eval_train = 0
seed = 11
"""


def _trainer(extra):
    tr = Trainer()
    for k, v in parse_config_string(CONF + extra):
        tr.set_param(k, v)
    tr.init_model()
    return tr


def _batch(x, y):
    b = DataBatch()
    b.data, b.label, b.batch_size = x, y, x.shape[0]
    return b


def test_update_period_matches_large_batch():
    rs = np.random.RandomState(0)
    x = rs.rand(8, 3, 6, 6).astype(np.float32)
    y = rs.randint(0, 5, (8, 1)).astype(np.float32)

    big = _trainer("batch_size = 8\n")
    small = _trainer("batch_size = 4\nupdate_period = 2\n")

    for step in range(3):
        big.update(_batch(x, y))
        small.update(_batch(x[:4], y[:4]))
        small.update(_batch(x[4:], y[4:]))
        assert small.epoch_counter == big.epoch_counter == step + 1

    for pb, ps in zip(big.params, small.params):
        assert sorted(pb) == sorted(ps)
        for k in pb:
            np.testing.assert_allclose(
                np.asarray(jax.device_get(pb[k])),
                np.asarray(jax.device_get(ps[k])),
                rtol=1e-5, atol=1e-6)


def test_update_period_composes_with_pipeline():
    """update_period=2 under pipeline_parallel: the packed stage-param
    tree accumulates like any other gradient leaf, so two half-batches
    must reproduce the single large-batch pipelined update."""
    rs = np.random.RandomState(1)
    x = rs.rand(16, 3, 6, 6).astype(np.float32)
    y = rs.randint(0, 5, (16, 1)).astype(np.float32)

    pp = "dev = cpu:0-1\npipeline_parallel = 2\npipeline_micro = 2\n"
    big = _trainer(pp + "batch_size = 16\n")
    small = _trainer(pp + "batch_size = 8\nupdate_period = 2\n")

    for _ in range(3):
        big.update(_batch(x, y))
        small.update(_batch(x[:8], y[:8]))
        small.update(_batch(x[8:], y[8:]))

    for pb, ps in zip(big.canonical_params(), small.canonical_params()):
        assert sorted(pb) == sorted(ps)
        for k in pb:
            np.testing.assert_allclose(
                np.asarray(jax.device_get(pb[k])),
                np.asarray(jax.device_get(ps[k])),
                rtol=2e-4, atol=2e-5)


def test_zero_sharded_optimizer_matches_plain():
    """update_on_server=1 (ZeRO weight-update sharding) is a layout change,
    not a math change: params after k steps match the replicated-optimizer
    run exactly (reference capability: server-side optimizer,
    src/nnet/nnet_ps_server.cpp:83-138)."""
    rs = np.random.RandomState(1)
    x = rs.rand(8, 3, 6, 6).astype(np.float32)
    y = rs.randint(0, 5, (8, 1)).astype(np.float32)

    plain = _trainer("batch_size = 8\ndev = cpu:0-7\n")
    zero = _trainer("batch_size = 8\ndev = cpu:0-7\nupdate_on_server = 1\n")
    for _ in range(3):
        plain.update(_batch(x, y))
        zero.update(_batch(x, y))
    for pp, pz in zip(plain.params, zero.params):
        for k in pp:
            np.testing.assert_allclose(
                np.asarray(jax.device_get(pp[k])),
                np.asarray(jax.device_get(pz[k])),
                rtol=1e-5, atol=1e-6)


def test_clip_global_norm():
    """clip_global_norm=c with plain SGD (no momentum/wd): the parameter
    step has global norm exactly lr*c when the raw gradient norm exceeds
    c (one shared scale preserves direction across tensors)."""
    extra = ("batch_size = 8\nmomentum = 0\nwd = 0\neta = 0.5\n"
             "clip_global_norm = 0.001\n")
    rs = np.random.RandomState(2)
    x = rs.rand(8, 3, 6, 6).astype(np.float32)
    y = rs.randint(0, 5, (8, 1)).astype(np.float32)
    tr = _trainer(extra)
    before = [{k: np.asarray(jax.device_get(v)) for k, v in p.items()}
              for p in tr.params]
    tr.update(_batch(x, y))
    delta_sq = 0.0
    for pb, pa in zip(before, tr.params):
        for k in pb:
            d = np.asarray(jax.device_get(pa[k])) - pb[k]
            delta_sq += float((d * d).sum())
    np.testing.assert_allclose(np.sqrt(delta_sq), 0.5 * 0.001, rtol=1e-4)
