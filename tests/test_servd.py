"""Serving frontend chaos suite (utils/servd.py): admission control +
load shedding, per-request deadlines, backend supervision + circuit
breaker (open / half-open probe / close), graceful SIGTERM drain, hot
reload, client-disconnect survival, and the statusd readiness-vs-liveness
split — all over real loopback sockets with injected backends.

Everything here is jax-free and cheap (the backend is a plain callable;
port 0 / loopback per memory of the tier-1 budget): the invariants under
fault injection are

* the server never crashes;
* every ACCEPTED request gets exactly one response line (an answer or an
  ``ERR <class>``);
* the counters reconcile: accepted == served + errors + shed + deadline;
* a drained shutdown loses zero accepted requests and exits 0.

The learn-task end-to-end wiring (real model, real generate failures)
lives in tests/test_decode.py::test_cli_serve_task.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from urllib.error import HTTPError
from urllib.request import urlopen

import pytest

from cxxnet_tpu.utils import servd, statusd, telemetry

from . import faultinject

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _lockrank_on(monkeypatch):
    """Runtime lock-order enforcement for every frontend/breaker/
    tracker this suite constructs (and the stub subprocesses it
    spawns): an inversion the static analyzer cannot see — callback-
    driven, cross-thread — fails the chaos test as a LockOrderError
    naming both locks and both sites instead of deadlocking in
    production (doc/static_analysis.md)."""
    monkeypatch.setenv("CXXNET_LOCKRANK", "1")


def echo(toks, seq):
    return [t + 1 for t in toks]


def reconciles(stats):
    return stats["accepted"] == (stats["served"] + stats["errors"]
                                 + stats["shed"] + stats["deadline"])


@pytest.fixture()
def make_frontend():
    """Factory for started+listening frontends; everything made here is
    drained at teardown (drain is idempotent, so tests may drain too)."""
    made = []

    def make(backend=echo, listen=True, **kw):
        kw.setdefault("drain_ms", 2000.0)
        fe = servd.ServeFrontend(backend, **kw)
        fe.start()
        if listen:
            fe.listen(0)
        made.append(fe)
        return fe

    yield make
    for fe in made:
        fe.drain(timeout_ms=2000)


# ----------------------------------------------------------------------
# basic protocol
def test_tcp_roundtrip_and_reconciliation(make_frontend):
    fe = make_frontend()
    assert faultinject.serve_request(fe.port, "1 2 3") == "2 3 4"
    assert faultinject.serve_request(fe.port, "10") == "11"
    assert faultinject.serve_request(fe.port, "DEADLINE 5000 7") == "8"
    stats = fe.drain()
    assert stats["served"] == 3 and stats["accepted"] == 3
    assert reconciles(stats)


def test_pipelined_requests_one_connection(make_frontend):
    import socket
    fe = make_frontend()
    with socket.create_connection(("127.0.0.1", fe.port),
                                  timeout=5) as c:
        c.sendall(b"1\n2\n3\n")
        f = c.makefile("r")
        assert [f.readline().strip() for _ in range(3)] == ["2", "3", "4"]


def test_pipelined_rejections_stay_in_request_order(make_frontend):
    """The protocol pairs responses to requests positionally, so a
    synchronous rejection (parse error: produced instantly by the reader
    thread) must NOT overtake the answer of an earlier request still
    occupying the worker."""
    import socket
    fe = make_frontend(backend=faultinject.slow_backend(echo, 0.1))
    with socket.create_connection(("127.0.0.1", fe.port),
                                  timeout=5) as c:
        # request 1 holds the worker for 100ms; 'bad x' would be
        # rejected immediately; request 3 queues behind
        c.sendall(b"1\nbad x\n3\n")
        f = c.makefile("r")
        lines = [f.readline().strip() for _ in range(3)]
    assert lines[0] == "2", lines
    assert lines[1].startswith("ERR parse"), lines
    assert lines[2] == "4", lines


def test_unterminated_final_line_is_served(make_frontend):
    """A client that forgets the trailing newline before shutting down
    its write side still gets its answer — the stdin surface serves an
    unterminated final line, so the TCP surface must too (silence here
    IS the framing-bug failure ERR empty exists to prevent)."""
    import socket
    fe = make_frontend()
    with socket.create_connection(("127.0.0.1", fe.port),
                                  timeout=5) as c:
        c.sendall(b"1 2 3")                 # no newline
        c.shutdown(socket.SHUT_WR)
        assert c.makefile("r").readline().strip() == "2 3 4"


def test_halfclosed_client_gets_slow_answer(make_frontend):
    """A client that pipelines its requests and shuts down its write
    side (normal use of a line protocol) must still receive an answer
    that takes longer than the drain budget — the connection waits for
    the response, it is not on a shutdown-related clock."""
    import socket
    fe = make_frontend(backend=faultinject.slow_backend(echo, 1.5),
                       drain_ms=100.0)
    with socket.create_connection(("127.0.0.1", fe.port),
                                  timeout=10) as c:
        c.sendall(b"1 2\n")
        c.shutdown(socket.SHUT_WR)
        assert c.makefile("r").readline().strip() == "2 3"
    stats = fe.stats()
    assert stats["served"] == 1 and stats["client_gone"] == 0


def test_empty_and_parse_rejections(make_frontend):
    fe = make_frontend(vocab=100)
    assert faultinject.serve_request(fe.port, "").startswith("ERR empty")
    assert faultinject.serve_request(
        fe.port, "   ").startswith("ERR empty")
    assert faultinject.serve_request(
        fe.port, "1 nope 2").startswith("ERR parse")
    assert faultinject.serve_request(
        fe.port, "1 999").startswith("ERR parse")
    assert faultinject.serve_request(
        fe.port, "DEADLINE abc 1").startswith("ERR parse")
    # float() accepts these, the protocol must not: a NaN deadline
    # compares False everywhere and silently disables the bound
    assert faultinject.serve_request(
        fe.port, "DEADLINE nan 1").startswith("ERR parse")
    assert faultinject.serve_request(
        fe.port, "DEADLINE inf 1").startswith("ERR parse")
    assert faultinject.serve_request(
        fe.port, "DEADLINE -5 1").startswith("ERR parse")
    assert faultinject.serve_request(
        fe.port, "DEADLINE 100").startswith("ERR empty")
    assert faultinject.serve_request(fe.port, "5 6") == "6 7"
    stats = fe.stats()
    assert stats["empty"] == 3 and stats["errors"] == 9
    assert stats["served"] == 1 and reconciles(stats)


def test_admin_stats_and_unknown(make_frontend):
    fe = make_frontend()
    faultinject.serve_request(fe.port, "1")
    resp = faultinject.serve_request(fe.port, "ADMIN stats")
    assert resp.startswith("OK") and "served=1" in resp
    assert faultinject.serve_request(
        fe.port, "ADMIN frobnicate").startswith("ERR parse")
    # admin lines are control traffic, outside the request reconciliation
    stats = fe.stats()
    assert stats["admin"] == 2 and stats["accepted"] == 1


# ----------------------------------------------------------------------
# deadlines
def test_deadline_expires_in_queue_before_dispatch(make_frontend):
    calls = []

    def counting_slow(toks, seq):
        calls.append(list(toks))
        time.sleep(0.15)
        return echo(toks, seq)

    fe = make_frontend(backend=counting_slow)
    results = {}

    def client(name, line):
        results[name] = faultinject.serve_request(fe.port, line)

    t1 = threading.Thread(target=client, args=("hold", "1 2 3"))
    t1.start()
    time.sleep(0.05)          # the 150ms request now occupies the worker
    t2 = threading.Thread(target=client, args=("doomed",
                                               "DEADLINE 20 4 5"))
    t2.start()
    t1.join()
    t2.join()
    assert results["hold"] == "2 3 4"
    assert results["doomed"].startswith("ERR deadline")
    # answered BEFORE dispatch: the backend never saw the doomed request
    assert [4, 5] not in calls
    stats = fe.stats()
    assert stats["deadline"] == 1 and reconciles(stats)


def test_default_deadline_from_conf(make_frontend):
    fe = make_frontend(backend=faultinject.slow_backend(echo, 0.15),
                       deadline_ms=20.0)
    r = faultinject.serve_flood(fe.port, ["1 2", "3 4"])
    # whichever request wins the worker occupies it past the other's
    # 20ms deadline; at most one can finish in time (and under load even
    # that one may expire before its own dispatch)
    ok = [x for x in r if not x.startswith("ERR")]
    dead = [x for x in r if x.startswith("ERR deadline")]
    assert len(ok) <= 1 and len(ok) + len(dead) == 2, r
    stats = fe.stats()
    assert stats["deadline"] >= 1 and reconciles(stats)


# ----------------------------------------------------------------------
# flood / shedding
def test_flood_sheds_and_every_request_answered(make_frontend):
    fe = make_frontend(backend=faultinject.slow_backend(echo, 0.08),
                       queue_size=2)
    responses = faultinject.serve_flood(fe.port, ["1 2"] * 10)
    assert all(r is not None for r in responses), responses
    ok = [r for r in responses if r == "2 3"]
    busy = [r for r in responses if r.startswith("ERR busy")]
    assert len(ok) + len(busy) == 10 and busy, responses
    stats = fe.stats()
    assert stats["accepted"] == 10
    assert stats["shed"] == len(busy) and stats["served"] == len(ok)
    assert reconciles(stats)


# ----------------------------------------------------------------------
# the ERR busy detail-token split (wire format: the fleet router's
# retryability contract — utils/routerd.py dispatches on token 3)
def test_err_busy_detail_tokens_queue_vs_breaker(make_frontend):
    """Queue-full and breaker-open sheds share the ``busy`` class (the
    2-token parse contract stands) but MUST be distinguishable by the
    third token: ``queue`` is instantly-retryable-elsewhere, ``breaker``
    additionally means "eject this replica from rotation"."""
    release = threading.Event()

    def wedged(toks, seq):
        release.wait(10.0)
        return echo(toks, seq)

    fe = make_frontend(backend=wedged, queue_size=1)
    try:
        fe.submit("1", lambda t: None)       # occupies the worker
        time.sleep(0.1)
        fe.submit("2", lambda t: None)       # fills the 1-slot queue
        resp = faultinject.serve_request(fe.port, "3")
        assert resp.split()[:3] == ["ERR", "busy", "queue"], resp
    finally:
        release.set()
    # breaker-open shed carries the breaker token (admission path)
    fe2 = make_frontend(backend=faultinject.exploding_backend(every=1),
                        breaker_fails=1, breaker_cooldown_ms=60000.0)
    assert faultinject.serve_request(
        fe2.port, "1").startswith("ERR backend")
    resp = faultinject.serve_request(fe2.port, "2")
    assert resp.split()[:3] == ["ERR", "busy", "breaker"], resp


def test_admin_stats_reports_live_load_gauges(make_frontend):
    """ADMIN stats carries the LIVE queue_depth / in_flight gauges (the
    router's load signal) alongside the counters — consistent with the
    admission queue at snapshot time."""
    release = threading.Event()

    def wedged(toks, seq):
        release.wait(10.0)
        return echo(toks, seq)

    fe = make_frontend(backend=wedged, queue_size=4)
    try:
        stats = faultinject.serve_request(fe.port, "ADMIN stats")
        assert "queue_depth=0" in stats and "in_flight=0" in stats
        fe.submit("1", lambda t: None)       # occupies the worker
        time.sleep(0.1)
        fe.submit("2", lambda t: None)       # queued
        fe.submit("3", lambda t: None)       # queued
        stats = faultinject.serve_request(fe.port, "ADMIN stats")
        assert "queue_depth=2" in stats and "in_flight=1" in stats, \
            stats
    finally:
        release.set()


# ----------------------------------------------------------------------
# backend supervision + circuit breaker
def test_backend_exception_answered_and_survived(make_frontend):
    fe = make_frontend(backend=faultinject.exploding_backend(echo,
                                                             every=2))
    assert faultinject.serve_request(fe.port, "1") == "2"
    assert faultinject.serve_request(
        fe.port, "1").startswith("ERR backend")
    assert faultinject.serve_request(fe.port, "1") == "2"
    assert faultinject.serve_request(
        fe.port, "1").startswith("ERR backend")
    stats = fe.stats()
    assert stats["served"] == 2 and stats["errors"] == 2
    assert fe.breaker.state == "closed"     # never 2 consecutive
    assert reconciles(stats)


def test_backend_returning_garbage_is_a_backend_error(make_frontend):
    """A backend that RETURNS a non-iterable-of-ints (None, a string of
    words, ...) must be answered ERR backend like one that raises — not
    kill the worker thread and strand every queued request."""
    results = iter([None, "not tokens", [5]])
    fe = make_frontend(backend=lambda toks, seq: next(results))
    assert faultinject.serve_request(
        fe.port, "1").startswith("ERR backend")
    assert faultinject.serve_request(
        fe.port, "1").startswith("ERR backend")
    assert faultinject.serve_request(fe.port, "1") == "5"
    assert fe.liveness_probe()[0], "worker thread died"
    assert reconciles(fe.stats())


def test_breaker_opens_sheds_and_recovers(make_frontend):
    backend = faultinject.healing_backend(echo, fail_first=2)
    fe = make_frontend(backend=backend, breaker_fails=2,
                       breaker_cooldown_ms=250.0)
    assert faultinject.serve_request(
        fe.port, "1").startswith("ERR backend")
    assert faultinject.serve_request(
        fe.port, "1").startswith("ERR backend")
    assert fe.breaker.state == "open"
    # open: shed instantly, backend NOT called
    assert faultinject.serve_request(fe.port, "1").startswith("ERR busy")
    assert backend.calls["n"] == 2
    # cooldown elapses; the healed backend's half-open probe closes it
    time.sleep(0.3)
    assert faultinject.serve_request(fe.port, "1") == "2"
    assert fe.breaker.state == "closed"
    stats = fe.stats()
    assert stats["shed"] == 1 and stats["served"] == 1
    assert reconciles(stats)


def test_breaker_halfopen_failure_doubles_cooldown(make_frontend):
    backend = faultinject.healing_backend(echo, fail_first=3)
    fe = make_frontend(backend=backend, breaker_fails=2,
                       breaker_cooldown_ms=200.0)
    for _ in range(2):
        assert faultinject.serve_request(
            fe.port, "1").startswith("ERR backend")
    assert fe.breaker.state == "open"
    time.sleep(0.25)
    # half-open probe fails (3rd injected failure): reopen, doubled
    assert faultinject.serve_request(
        fe.port, "1").startswith("ERR backend")
    assert fe.breaker.state == "open"
    assert faultinject.serve_request(fe.port, "1").startswith("ERR busy")
    time.sleep(0.45)                     # past the doubled 400ms cooldown
    assert faultinject.serve_request(fe.port, "1") == "2"
    assert fe.breaker.state == "closed"
    assert fe.breaker.opens == 0         # reset on close


# ----------------------------------------------------------------------
# client disconnect mid-request
def test_client_disconnect_mid_request_survived(make_frontend):
    fe = make_frontend(backend=faultinject.slow_backend(echo, 0.1))
    faultinject.disconnecting_client(fe.port, "1 2 3")
    time.sleep(0.3)           # worker answers into the dead socket
    # the server survives and keeps serving
    assert faultinject.serve_request(fe.port, "5") == "6"
    stats = fe.stats()
    assert stats["accepted"] == 2 and reconciles(stats)


# ----------------------------------------------------------------------
# hot reload
def test_admin_reload_between_requests_keeps_queue(make_frontend):
    model = {"v": 1}
    reloads = []

    def backend(toks, seq):
        time.sleep(0.05)
        return [t + model["v"] for t in toks]

    def reload_fn():
        model["v"] = 10
        reloads.append(1)
        return True

    fe = make_frontend(backend=backend, reload_fn=reload_fn)
    import socket
    with socket.create_connection(("127.0.0.1", fe.port),
                                  timeout=5) as c:
        f = c.makefile("r")
        c.sendall(b"1\n")
        assert f.readline().strip() == "2"      # pre-reload model
        # a reload scheduled with requests already queued behind it:
        # nothing is dropped, the swap lands between requests, and the
        # queued requests are served by the NEW model
        c.sendall(b"ADMIN reload\n1\n1\n")
        lines = [f.readline().strip() for _ in range(3)]
    assert lines[0].startswith("OK reload")
    assert lines[1:] == ["11", "11"] and reloads
    assert fe.stats()["reloads"] == 1


def test_failing_reload_keeps_model_and_serving(make_frontend, capsys):
    def reload_fn():
        raise RuntimeError("no checkpoint dir")

    fe = make_frontend(reload_fn=reload_fn)
    assert faultinject.serve_request(
        fe.port, "ADMIN reload").startswith("OK")
    assert faultinject.serve_request(fe.port, "1") == "2"
    assert fe.stats()["reloads"] == 0


# ----------------------------------------------------------------------
# drain
def test_drain_answers_every_accepted_request():
    fe = servd.ServeFrontend(faultinject.slow_backend(echo, 0.15),
                             queue_size=16, drain_ms=10000.0)
    fe.start()
    replies = []
    for i in range(4):
        fe.submit("%d" % i, replies.append)
    stats = fe.drain()          # generous budget: everything is served
    assert sorted(replies) == ["1", "2", "3", "4"]
    assert stats["served"] == 4 and reconciles(stats)


def test_drain_budget_exhausted_still_answers():
    fe = servd.ServeFrontend(faultinject.slow_backend(echo, 0.2),
                             queue_size=16)
    fe.start()
    replies = []
    for i in range(5):
        fe.submit("%d" % i, replies.append)
    stats = fe.drain(timeout_ms=150)
    # exactly one response per accepted request: some served, the
    # leftovers explicitly ERR draining — never silence
    assert len(replies) == 5
    assert any(r.startswith("ERR draining") for r in replies)
    assert stats["served"] >= 1 and reconciles(stats)
    # post-drain admissions are refused, and still answered
    fe.submit("9", replies.append)
    assert replies[-1].startswith("ERR draining")


def test_drain_leftovers_burn_slo_budget():
    """Queued requests a drain gives up on (ERR draining) are accepted
    requests the client lost: they must burn error budget like an
    admission shed, or a preemption during overload leaves
    cxxnet_slo_burn reading 0 with every accepted request failed."""
    slo = statusd.SLOTracker(availability=0.999, min_requests=4,
                             min_bad=3, window_s=60.0)
    fe = servd.ServeFrontend(faultinject.slow_backend(echo, 0.5),
                             queue_size=16, slo=slo)
    fe.start()
    replies = []
    for i in range(6):
        fe.submit("%d" % i, replies.append)
    stats = fe.drain(timeout_ms=50)
    assert len(replies) == 6 and reconciles(stats)
    drained = sum(1 for r in replies if r.startswith("ERR draining"))
    assert drained >= 3, replies
    snap = slo.snapshot()
    assert snap["bad"] >= drained, snap
    assert snap["alert"] == 1, snap


def test_stalled_backend_fails_readiness_then_liveness():
    """A backend that BLOCKS without raising is invisible to deadlines
    (pre-dispatch only), the breaker (no exception), and the paused
    worker heartbeat — the stall_after_s bound on the in-flight
    dispatch is what surfaces it: readiness fails past the bound,
    liveness past twice it, both recover when the backend returns."""
    release = threading.Event()

    def wedged(toks, seq):
        release.wait(10.0)
        return echo(toks, seq)

    fe = servd.ServeFrontend(wedged, stall_after_s=0.1, drain_ms=500.0)
    fe.start()
    try:
        fe.submit("1", lambda t: None)
        time.sleep(0.05)            # in flight, under the bound
        assert fe.health_probe()[0] and fe.liveness_probe()[0]
        time.sleep(0.1)             # past stall_after_s: unroutable
        ok, detail = fe.health_probe()
        assert not ok and "stalled" in detail
        assert fe.liveness_probe()[0]     # but not restart-worthy yet
        time.sleep(0.15)            # past 2x: restart signal
        ok, detail = fe.liveness_probe()
        assert not ok and "wedged" in detail
    finally:
        release.set()
    time.sleep(0.2)                 # backend returned: healthy again
    assert fe.health_probe()[0] and fe.liveness_probe()[0]
    fe.drain()


def test_drain_with_wedged_backend_answers_inflight_once():
    """A backend that outlives even the drain budget: the in-flight
    request is answered ERR by drain itself (never silently dropped),
    the final stats reconcile, and when the wedged backend eventually
    returns, the worker's late answer is a no-op — one response line,
    one outcome count, ever."""
    release = threading.Event()

    def wedged(toks, seq):
        release.wait(10.0)
        return echo(toks, seq)

    fe = servd.ServeFrontend(wedged, drain_ms=200.0)
    fe.start()
    replies = []
    fe.submit("1", replies.append)
    time.sleep(0.1)                  # request is in flight
    try:
        stats = fe.drain(timeout_ms=200)
        assert replies and replies[0].startswith("ERR draining"), replies
        assert reconciles(stats) and stats["errors"] == 1
    finally:
        release.set()                # un-wedge the worker thread
    time.sleep(0.3)                  # its late answer must be a no-op
    assert len(replies) == 1
    final = fe.stats()
    assert reconciles(final) and final["served"] == 0
    # the late completion is flight-recorded as abandoned — the backend
    # did the work, but the client got drain's ERR, not this answer
    recs = [r for r in fe.flight.list() if r["outcome"] == "abandoned"]
    assert len(recs) == 1, fe.flight.list()
    assert not any(r["outcome"] == "served" for r in fe.flight.list())


def test_sigterm_drain_loses_zero_accepted_requests():
    """The headline drain contract, against the real process boundary:
    SIGTERM mid-flight → the stub server stops accepting, finishes every
    accepted request, reports reconciled stats, exits 0 — and the
    clients' received responses account for every accepted request."""
    p = subprocess.Popen(
        [sys.executable, "-m", "cxxnet_tpu.utils.servd", "--stub",
         "--delay-ms", "60"],
        stdout=subprocess.PIPE, text=True, cwd=REPO)
    try:
        port = int(p.stdout.readline().split()[-1])
        responses = []
        lock = threading.Lock()

        def client():
            r = faultinject.serve_request(port, "1 2 3", timeout=15)
            with lock:
                responses.append(r)

        ts = [threading.Thread(target=client) for _ in range(8)]
        for t in ts:
            t.start()
        time.sleep(0.15)        # a couple served, the rest queued
        p.send_signal(signal.SIGTERM)
        for t in ts:
            t.join()
        rc = p.wait(timeout=20)
        tail = p.stdout.read()
    finally:
        if p.poll() is None:
            p.kill()
            p.wait()
    assert rc == 0, tail
    stats = json.loads(tail.split("drained ", 1)[1])
    assert reconciles(stats)
    # zero accepted-but-unanswered: every request the server accepted
    # produced a response line some client received
    answered = [r for r in responses if r is not None]
    assert len(answered) == stats["accepted"]
    assert all(r == "2 3 4" or r.startswith("ERR") for r in answered)


# ----------------------------------------------------------------------
# statusd readiness vs liveness (the /healthz split, satellite of this
# PR: 503 while draining or breaker-open, /livez unaffected)
@pytest.fixture()
def status_server():
    reg = telemetry._Registry()
    reg.enable()
    srv = statusd.StatusServer(0, host="127.0.0.1",
                               registry=reg).start()
    yield srv
    srv.stop()
    reg.disable()


def _get(srv, path):
    try:
        r = urlopen("http://127.0.0.1:%d%s" % (srv.port, path), timeout=5)
        return r.status, r.read().decode()
    except HTTPError as e:
        return e.code, e.read().decode()


def test_healthz_flips_on_breaker_and_recovers(make_frontend,
                                               status_server):
    backend = faultinject.healing_backend(echo, fail_first=2)
    fe = make_frontend(backend=backend, breaker_fails=2,
                       breaker_cooldown_ms=200.0)
    status_server.register_probe("serving", fe.health_probe)
    status_server.register_probe("serving.worker", fe.liveness_probe,
                                 liveness=True)
    assert _get(status_server, "/healthz")[0] == 200
    assert _get(status_server, "/livez")[0] == 200
    for _ in range(2):
        faultinject.serve_request(fe.port, "1")
    code, body = _get(status_server, "/healthz")
    assert code == 503 and "circuit breaker open" in body
    # breaker-open is NOT-READY, not NOT-ALIVE: no restart for overload
    assert _get(status_server, "/livez")[0] == 200
    metrics = _get(status_server, "/metrics")[1]
    assert 'cxxnet_healthy{process="0"} 0' in metrics
    assert 'cxxnet_live{process="0"} 1' in metrics
    # successful half-open probe closes the breaker: ready again
    time.sleep(0.25)
    assert faultinject.serve_request(fe.port, "1") == "2"
    assert _get(status_server, "/healthz")[0] == 200
    assert 'cxxnet_healthy{process="0"} 1' \
        in _get(status_server, "/metrics")[1]


def test_healthz_flips_during_drain_livez_stays(make_frontend,
                                                status_server):
    fe = make_frontend()
    status_server.register_probe("serving", fe.health_probe)
    status_server.register_probe("serving.worker", fe.liveness_probe,
                                 liveness=True)
    assert _get(status_server, "/healthz")[0] == 200
    fe.drain()
    code, body = _get(status_server, "/healthz")
    assert code == 503 and "draining" in body
    assert _get(status_server, "/livez")[0] == 200


# ----------------------------------------------------------------------
# watchdog heartbeat channels
def test_watchdog_worker_channel_pauses_when_idle(make_frontend):
    """The serve.worker channel must disarm across idle periods (an
    empty queue is not a hang) while serve.accept keeps beating from the
    accept poll loop — so a watchdog over a quiet server never
    false-alarms."""
    from cxxnet_tpu.utils import health
    wd = health.Watchdog(timeout=1.0, action="warn", poll=30.0).start()
    try:
        fe = make_frontend()
        assert faultinject.serve_request(fe.port, "1") == "2"
        time.sleep(0.3)        # idle: the worker paused its channel
        chans = {c[0]: c[3] for c in health.channel_status()}
        assert "serve.worker" not in chans
        assert chans.get("serve.accept") is False       # armed, fresh
    finally:
        wd.stop()


# ----------------------------------------------------------------------
# stdin-engine path (submit wait=True) + metrics surfacing
def test_sync_submit_keeps_request_order():
    fe = servd.ServeFrontend(echo, drain_ms=2000.0)
    fe.start()
    replies = []
    for line in ("1", "", "2 x", "3"):
        fe.submit(line, replies.append, wait=True)
    assert replies[0] == "2"
    assert replies[1].startswith("ERR empty")
    assert replies[2].startswith("ERR parse")
    assert replies[3] == "4"
    fe.drain()


def test_serve_metrics_reach_prometheus(status_server):
    reg = status_server.registry
    # the frontend records through the module-level telemetry registry;
    # here the series are injected directly to pin the /metrics names
    reg.count("serve.accepted", 10)
    reg.count("serve.requests", 7)
    reg.count("serve.shed", 2)
    reg.count("serve.deadline", 1)
    reg.gauge("serve.queue_depth", 3)
    reg.gauge("serve.in_flight", 1)
    reg.hist("serve.request", 0.05)
    reg.hist("serve.queue_wait", 0.01)
    code, text = _get(status_server, "/metrics")
    assert code == 200
    for needle in ("cxxnet_serve_accepted_total 10",
                   "cxxnet_serve_requests_total 7",
                   "cxxnet_serve_shed_total 2",
                   "cxxnet_serve_deadline_total 1",
                   "cxxnet_serve_queue_depth 3",
                   "cxxnet_serve_in_flight 1"):
        assert needle.split()[0] in text and needle.replace(
            needle.split()[0],
            needle.split()[0] + '{process="0"}') in text, needle
    assert "cxxnet_serve_request_seconds_bucket" in text
    assert "cxxnet_serve_queue_wait_seconds_bucket" in text
    reg.hist("serve.ttft", 0.02)
    reg.gauge("serve.tokens_per_second", 120.5)
    reg.gauge("serve.batch_occupancy", 1)
    text = _get(status_server, "/metrics")[1]
    assert "cxxnet_serve_ttft_seconds_bucket" in text
    assert "cxxnet_serve_tokens_per_second" in text
    assert 'cxxnet_serve_batch_occupancy{process="0"} 1' in text


# ----------------------------------------------------------------------
# tools/telemetry_report.py serving section + unresolved-breaker gate
sys.path.insert(0, os.path.join(REPO, "tools"))
import telemetry_report  # noqa: E402


def _serve_into_log(tmp_path, backend, requests, **kw):
    """Run a frontend against the module-level telemetry registry with a
    real JSONL sink (the learn-task layout), return the log path."""
    log = str(tmp_path / "serve.jsonl")
    telemetry.enable(log)
    try:
        fe = servd.ServeFrontend(backend, **kw)
        fe.start()
        port = fe.listen(0)
        for line in requests:
            faultinject.serve_request(port, line)
        fe.drain()
    finally:
        telemetry.finish(close=True)
    return log


def test_report_serving_section_and_rates(tmp_path, capsys):
    backend = faultinject.healing_backend(echo, fail_first=2)
    log = _serve_into_log(
        tmp_path, backend,
        ["1 2", "3", "4", "5", "DEADLINE 0 6", "7 8"],
        breaker_fails=2, breaker_cooldown_ms=1.0, queue_size=8,
        drain_ms=2000.0)
    # 2 backend failures open the breaker; the 1ms cooldown means the
    # next request probes and (healed) closes it — the log ends healthy
    rc = telemetry_report.main([log, "--json"])
    agg = json.loads(capsys.readouterr().out)
    assert rc == 0
    sv = agg["serving"]
    assert sv["accepted"] == 6 and sv["errors"] == 2
    assert sv["deadline"] == 1 and sv["deadline_miss_rate"] > 0
    assert sv["breaker_transitions"]["open"] == 1
    assert sv["breaker_final"] == {"0": "closed"}
    assert agg["hists"]["serve.request"]["count"] >= 3
    rc = telemetry_report.main([log])
    out = capsys.readouterr().out
    assert rc == 0 and "== serving ==" in out
    assert "breaker transitions" in out


def test_report_serving_section_empty_latency_renders_na(tmp_path, capsys):
    """A run whose only accepted request dies in the queue (deadline 0,
    answered before dispatch) leaves the declared serve.request
    histogram empty — count 0, None percentiles. The serving section's
    latency line must render n/a, not crash on the None sentinel."""
    log = _serve_into_log(tmp_path, echo, ["DEADLINE 0 1"], drain_ms=500.0)
    rc = telemetry_report.main([log])
    out = capsys.readouterr().out
    assert rc == 0 and "== serving ==" in out
    assert "request latency: n=0  p50=n/a  p90=n/a  p99=n/a" in out


def test_report_exit2_on_unresolved_breaker_open(tmp_path, capsys):
    log = _serve_into_log(
        tmp_path, faultinject.exploding_backend(every=1),
        ["1", "2", "3"],
        breaker_fails=2, breaker_cooldown_ms=60000.0, drain_ms=500.0)
    rc = telemetry_report.main([log])
    err = capsys.readouterr().err
    assert rc == 2
    assert "circuit breaker still OPEN" in err


# ----------------------------------------------------------------------
# request tracing: ids, phase attribution, TTFT split, flight recorder,
# /trace?request=<id>, SLO burn (the observability contract the
# throughput arc is graded against — ISSUE 6 tentpole)
PHASES = telemetry.REQUEST_PHASES


def test_request_tracing_end_to_end():
    """The acceptance loop: a loopback serve run answers
    /trace?request=<id> for a just-completed request with a Chrome
    trace whose phase spans cover >= 95% of the request's wall-clock,
    /requestz lists it, and /metrics exports valid serve_ttft_seconds
    buckets."""
    telemetry.enable()        # module registry: the frontend's series
    fe = srv = None
    try:
        srv = statusd.StatusServer(0, host="127.0.0.1").start()
        fe = servd.ServeFrontend(
            faultinject.phased_backend(echo, prefill_s=0.03,
                                       per_token_s=0.005),
            drain_ms=2000.0)
        fe.start()
        fe.listen(0)
        srv.flight = fe.flight
        assert faultinject.serve_request(fe.port, "1 2 3") == "2 3 4"
        rec = fe.flight.list()[0]
        assert rec["outcome"] == "served" and rec["tokens_out"] == 3
        # coverage vs the independently measured accept->observe
        # wall-clock (wall_s), NOT the phase sum total_s — total_s IS
        # the sum, so an assertion against it could never fail
        cover = sum(rec["phases"].values())
        assert cover >= 0.95 * rec["wall_s"]
        # the per-request Chrome trace over HTTP
        code, body = _get(srv, "/trace?request=" + rec["id"])
        assert code == 200
        xs = [e for e in json.loads(body)["traceEvents"]
              if e.get("ph") == "X" and e["name"] in PHASES]
        total_us = max(e["ts"] + e["dur"] for e in xs) \
            - min(e["ts"] for e in xs)
        assert sum(e["dur"] for e in xs) >= 0.95 * total_us
        assert total_us >= 0.95 * rec["wall_s"] * 1e6
        code, _ = _get(srv, "/trace?request=99999")
        assert code == 404
        code, body = _get(srv, "/requestz?json=1")
        assert code == 200
        assert rec["id"] in [r["id"]
                             for r in json.loads(body)["requests"]]
        # HTML by default (the /fleetz//programz ?json=1 contract) and
        # the single-record fetch the cross-process stitch uses
        code, body = _get(srv, "/requestz")
        assert code == 200 and "flight recorder" in body
        code, body = _get(srv, "/requestz?request=" + rec["id"])
        assert code == 200 and json.loads(body)["id"] == rec["id"]
        # /metrics: valid serve_ttft_seconds buckets with the request in
        code, metrics = _get(srv, "/metrics")
        assert code == 200
        for line in metrics.splitlines():
            if line and not line.startswith("#"):
                assert statusd.PROM_LINE_RE.match(line), line
        m = [line for line in metrics.splitlines()
             if line.startswith("cxxnet_serve_ttft_seconds_bucket")
             and 'le="+Inf"' in line]
        assert m and int(m[0].rsplit(" ", 1)[1]) >= 1, m
    finally:
        if fe is not None:
            fe.drain(timeout_ms=2000)
        if srv is not None:
            srv.stop()
        telemetry.disable()


def test_ttft_split_phase_attribution(make_frontend):
    """The first_token mark splits the backend call into prefill and
    decode; TTFT = queue_wait + dispatch + prefill, strictly less than
    the total for a multi-token answer."""
    fe = make_frontend(backend=faultinject.phased_backend(
        echo, prefill_s=0.05, per_token_s=0.01))
    assert faultinject.serve_request(fe.port, "1 2 3") == "2 3 4"
    rec = fe.flight.list()[0]
    ph = rec["phases"]
    assert ph["prefill"] >= 0.04, ph          # slept 50ms pre-mark
    assert ph["decode"] >= 0.015, ph          # 2 x 10ms post-mark
    # fields round to 6 decimals independently: allow one ulp per term
    assert abs(rec["ttft_s"] - (ph["queue_wait"] + ph["dispatch"]
                                + ph["prefill"])) < 5e-6
    assert rec["ttft_s"] <= rec["total_s"] - 0.01
    assert rec["tokens_per_s"] is not None and rec["tokens_per_s"] > 0


def test_unmarked_backend_falls_back_to_all_prefill(make_frontend):
    """A backend that never marks first_token (no trainer underneath)
    still gets honest attribution: first and last token arrive
    together, so the whole call is prefill and TTFT == total latency
    minus nothing."""
    fe = make_frontend()
    assert faultinject.serve_request(fe.port, "7") == "8"
    rec = fe.flight.list()[0]
    assert rec["phases"]["decode"] == 0.0
    assert abs(rec["ttft_s"] - rec["total_s"]) < 1e-9


def test_trace_context_tags_backend_telemetry(make_frontend):
    """Spans/compiles/counters recorded inside the backend carry the
    request id (telemetry.trace_context propagation through the worker)
    and land attributed in the flight record."""
    telemetry.enable()
    try:
        def backend(toks, seq):
            telemetry.count("decode.tokens", len(toks))
            telemetry.record_compile("jit.decode_step",
                                     "new_signature", 0.01)
            return [t + 1 for t in toks]

        fe = make_frontend(backend=backend)
        assert faultinject.serve_request(fe.port, "5 6") == "6 7"
        rec = fe.flight.list()[0]
        assert [c["name"] for c in rec["recompiles"]] \
            == ["jit.decode_step"]
        assert rec["counts"]["decode.tokens"] == 2
        evs = telemetry.recent_events()
        spans = [e for e in evs if e.get("ev") == "span"
                 and e.get("name") == "serve.request"]
        assert spans and spans[-1].get("req") == rec["id"]
        comps = [e for e in evs if e.get("ev") == "compile"]
        assert comps and comps[-1].get("req") == rec["id"]
        done = [e for e in evs if e.get("ev") == "serve_request_done"]
        assert done and done[-1]["recompiles"] == 1
    finally:
        telemetry.disable()


def test_request_ids_unique_and_deadline_attributed(make_frontend):
    """Ids increase per accepted request; a request that dies in the
    queue (deadline) still leaves a flight record, attributed to
    queue_wait with no backend phases."""
    started = threading.Event()

    def slow(toks, seq):
        started.set()
        time.sleep(0.08)
        return echo(toks, seq)

    fe = make_frontend(backend=slow, queue_size=8)
    # occupy the worker first so the deadlined request is GUARANTEED to
    # out-wait its 10ms budget in the queue (no dispatch-order race)
    first = threading.Thread(
        target=lambda: faultinject.serve_request(fe.port, "1"))
    first.start()
    assert started.wait(5.0)
    resp = faultinject.serve_request(fe.port, "DEADLINE 10 2")
    first.join()
    assert resp.startswith("ERR deadline")
    assert faultinject.serve_request(fe.port, "3") == "4"
    recs = fe.flight.list()
    assert len({r["id"] for r in recs}) == 3
    dl = next(r for r in recs if r["outcome"] == "deadline")
    assert dl["phases"]["prefill"] == 0.0 \
        and dl["phases"]["queue_wait"] > 0 and dl["ttft_s"] is None


def test_flight_recorder_eviction(make_frontend):
    fr = telemetry.FlightRecorder(cap=4)
    for i in range(7):
        fr.record({"id": str(i)})
    assert len(fr) == 4
    assert fr.get("2") is None and fr.get("6")["id"] == "6"
    assert [r["id"] for r in fr.list()] == ["6", "5", "4", "3"]
    # and through the frontend: the ring holds only the newest
    fe = make_frontend(flight_cap=2)
    for line in ("1", "2", "3", "4"):
        faultinject.serve_request(fe.port, line)
    assert len(fe.flight) == 2
    assert [r["tokens_in"] for r in fe.flight.list()] == [1, 1]
    assert fe.flight.get(fe.flight.list()[0]["id"]) is not None


def test_slo_burn_flips_on_slow_flood_not_on_healthy(make_frontend):
    slo = statusd.SLOTracker(ttft_ms=50.0, availability=0.999,
                             min_requests=5, window_s=60.0)
    fe = make_frontend(slo=slo)
    for _ in range(5):
        assert faultinject.serve_request(fe.port, "1") == "2"
    snap = slo.snapshot()
    assert snap["alert"] == 0 and snap["burn_rate"] == 0.0, snap
    # injected slow-request flood: every TTFT blows the 50ms objective
    fe.backend = faultinject.slow_backend(echo, 0.08)
    responses = faultinject.serve_flood(fe.port, ["1"] * 6)
    assert all(r == "2" for r in responses)
    snap = slo.snapshot()
    assert snap["alert"] == 1 and snap["burn_rate"] >= 1.0, snap
    assert snap["by_reason"]["ttft"] >= 6, snap


def test_admission_sheds_burn_slo_budget(make_frontend):
    """Requests shed at the door (queue full / breaker open at accept)
    are availability failures: they must burn the SLO error budget
    exactly like dispatch-time sheds, or a total-overload flood that
    sheds 99% of traffic reads as burn 0 during the worst availability
    incident the server can have."""
    release = threading.Event()

    def wedged(toks, seq):
        release.wait(10.0)
        return echo(toks, seq)

    slo = statusd.SLOTracker(availability=0.99, min_requests=3,
                             window_s=60.0)
    fe = make_frontend(backend=wedged, queue_size=1, slo=slo)
    try:
        fe.submit("1", lambda t: None)   # occupies the worker
        time.sleep(0.1)
        fe.submit("2", lambda t: None)   # fills the 1-slot queue
        sheds = [faultinject.serve_request(fe.port, "3")
                 for _ in range(4)]
        assert all(s.startswith("ERR busy") for s in sheds), sheds
        snap = slo.snapshot()
        assert snap["bad"] >= 4 and snap["by_reason"]["error"] >= 4, snap
        assert snap["alert"] == 1, snap
    finally:
        release.set()


def test_report_request_breakdown_and_slo_exit2(tmp_path, capsys):
    slo = statusd.SLOTracker(ttft_ms=5.0, availability=0.99,
                             min_requests=3, window_s=60.0)
    log = _serve_into_log(
        tmp_path,
        faultinject.phased_backend(echo, prefill_s=0.02,
                                   per_token_s=0.001),
        ["1 2", "3 4", "5 6", "7 8", "DEADLINE 0 9 9"], slo=slo,
        drain_ms=2000.0)
    rc = telemetry_report.main([log, "--json"])
    agg = json.loads(capsys.readouterr().out)
    # every request blew the 5ms TTFT objective: the log ends burning
    assert rc == 2
    rq = agg["requests"]
    assert rq["count"] == 5
    assert rq["outcomes"] == {"served": 4, "deadline": 1}
    # the deadline-expired request never reached the backend: its event
    # carries null prefill/decode (hard zeros would deflate the latency
    # percentiles exactly during the overload this table triages), but
    # its queue_wait/dispatch/total are real
    for ph in ("queue_wait", "dispatch", "total"):
        assert rq["phases"][ph]["count"] == 5, ph
    for ph in ("prefill", "decode", "ttft"):
        assert rq["phases"][ph]["count"] == 4, ph
    assert rq["phases"]["prefill"]["p50_ms"] >= 15.0
    assert len(rq["slowest"]) == 5
    assert agg["slo"]["burning"] == ["0"]
    rc = telemetry_report.main([log])
    captured = capsys.readouterr()
    assert rc == 2
    assert "request breakdown" in captured.out
    assert "top-5 slowest requests" in captured.out
    assert "burn rate still exceeded" in captured.err


# ----------------------------------------------------------------------
# continuous batching: the slot-backend dispatcher (doc/serving.md
# "Continuous batching") driven jax-free through faultinject's fake
# slot backend — coalescing, mid-decode join, per-iteration deadlines,
# exactly-once under drain mid-batch, load/occupancy signals.


def _expect_line(first_tok, n):
    return " ".join(str(first_tok + k) for k in range(1, n + 1))


def test_batch_coalesce_flood_exact_and_occupancy(make_frontend):
    """A concurrent flood coalesces into real batches: every response is
    exact (zero lost, zero duplicated — one aligned answer per
    request), the measured mean occupancy beats 1 sequence/pass, and
    every flight record carries occupancy_at_dispatch."""
    sb = faultinject.slot_backend(buckets=(1, 2, 4), n_new=4,
                                  per_token_s=0.003)
    fe = make_frontend(None, slot_backend=sb, batch_max=4,
                       batch_window_ms=40.0)
    lines = ["%d 7" % (10 * i) for i in range(1, 9)]
    resps = faultinject.serve_flood(fe.port, lines, timeout=20.0)
    for i, r in enumerate(resps):
        assert r == _expect_line(10 * (i + 1), 4), (i, r)
    assert fe.mean_occupancy() is not None and fe.mean_occupancy() > 1.0
    recs = fe.flight.list()
    assert all(r.get("occupancy_at_dispatch", 0) >= 1 for r in recs)
    assert any(r["occupancy_at_dispatch"] > 1 for r in recs)
    stats = fe.drain()
    assert reconciles(stats)
    assert stats["accepted"] == stats["served"] == 8


def test_batch_mid_decode_join_after_retire(make_frontend):
    """THE headline: a finished sequence frees its slot and the next
    queued request joins while a straggler is still decoding —
    asserted via the fake backend's iteration journal, with exact
    responses for all three."""
    sb = faultinject.slot_backend(buckets=(2,), n_new=3,
                                  per_token_s=0.01, long_for={100},
                                  long_n_new=40)
    fe = make_frontend(None, slot_backend=sb, batch_max=2,
                       batch_window_ms=40.0, drain_ms=8000.0)
    out = [None] * 3

    def ask(i, line):
        out[i] = faultinject.serve_request(fe.port, line, timeout=30.0)

    t1 = threading.Thread(target=ask, args=(0, "100"))   # straggler: 40
    t2 = threading.Thread(target=ask, args=(1, "200"))   # 3 tokens
    t1.start()
    t2.start()
    time.sleep(0.15)                 # straggler mid-decode, 200 done
    t3 = threading.Thread(target=ask, args=(2, "300"))
    t3.start()
    for t in (t1, t2, t3):
        t.join()
    assert out[0] == _expect_line(100, 40)
    assert out[1] == _expect_line(200, 3)
    assert out[2] == _expect_line(300, 3)
    admits = [e for e in sb.journal if e[0] == "admit"]
    retires = [e for e in sb.journal if e[0] == "retire"]
    # request 300 (3rd admit) joined AFTER the first retirement freed a
    # slot and BEFORE the straggler finished: a mid-decode join, pinned
    # by iteration counters, not timing
    join_iter = admits[2][2]
    first_retire_iter = retires[0][2]
    straggler_retire_iter = retires[-1][2]
    assert first_retire_iter <= join_iter < straggler_retire_iter, \
        sb.journal
    stats = fe.drain()
    assert reconciles(stats) and stats["served"] == 3


def test_batch_deadline_retires_mid_decode_others_continue(make_frontend):
    """Per-ITERATION deadline enforcement: an expired sequence retires
    with ERR deadline between iterations while its batchmates keep
    decoding to completion; its flight record keeps the real phases
    (the backend burned them) and its tokens so far."""
    sb = faultinject.slot_backend(buckets=(2,), n_new=40,
                                  per_token_s=0.005)
    fe = make_frontend(None, slot_backend=sb, batch_max=2,
                       batch_window_ms=50.0, drain_ms=8000.0)
    out = [None] * 2

    def ask(i, line):
        out[i] = faultinject.serve_request(fe.port, line, timeout=30.0)

    ts = [threading.Thread(target=ask, args=(0, "DEADLINE 100 100")),
          threading.Thread(target=ask, args=(1, "200"))]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert out[0].startswith("ERR deadline"), out[0]
    assert out[1] == _expect_line(200, 40)
    stats = fe.drain()
    assert reconciles(stats)
    assert stats["deadline"] == 1 and stats["served"] == 1
    # the retired sequence really decoded before expiring: its record
    # carries tokens and a positive decode phase (not the hard zeros of
    # a never-dispatched expiry)
    rec = next(r for r in fe.flight.list() if r["outcome"] == "deadline")
    assert rec["tokens_out"] >= 1
    assert rec["phases"]["decode"] > 0


def test_batch_drain_mid_batch_exactly_once(make_frontend):
    """Drain with a batch in flight and more queued: every accepted
    request is answered EXACTLY once — completed, ERR draining
    (queued leftovers), or ERR draining backend (the batch the budget
    gave up on) — and the books reconcile."""
    sb = faultinject.slot_backend(buckets=(2,), n_new=30,
                                  per_token_s=0.02)
    fe = make_frontend(None, slot_backend=sb, listen=False, batch_max=2,
                       batch_window_ms=0.0, drain_ms=300.0)
    replies = {}

    def mkreply(i):
        def reply(text):
            replies.setdefault(i, []).append(text)
        return reply

    for i in range(4):                  # 2 into slots, 2 queued
        fe.submit("%d00 7" % (i + 1), mkreply(i))
    time.sleep(0.15)                    # batch underway
    stats = fe.drain(timeout_ms=300)
    assert reconciles(stats), stats
    assert stats["accepted"] == 4
    time.sleep(0.3)                     # a late worker answer would dup
    assert sorted(replies) == [0, 1, 2, 3]
    for i, texts in sorted(replies.items()):
        assert len(texts) == 1, (i, texts)
    assert sum(1 for t in replies.values()
               if t[0].startswith("ERR draining")) >= 2


def test_batch_step_failure_fails_whole_batch_then_recovers(
        make_frontend):
    """A decode-step exception answers every active sequence ERR
    backend (exactly once), counts ONE breaker failure, drops the
    session — and the next request gets a fresh session and succeeds."""
    sb = faultinject.slot_backend(buckets=(2,), n_new=4,
                                  per_token_s=0.005,
                                  explode_on_iterations={2})
    fe = make_frontend(None, slot_backend=sb, batch_max=2,
                       batch_window_ms=50.0)
    resps = faultinject.serve_flood(fe.port, ["100", "200"],
                                    timeout=20.0)
    assert all(r.startswith("ERR backend") for r in resps), resps
    assert fe.breaker.state == "closed"     # 1 failure < the threshold
    # recovery: a NEW session serves the next request (iteration 2 of
    # the fresh session explodes again — use a session whose first
    # explosion is spent... the fake's explode set is per-session, so
    # drive past it with single-token steps)
    sb.explode_on.clear()
    assert faultinject.serve_request(fe.port, "300",
                                     timeout=20.0) == _expect_line(300, 4)
    assert len(sb.sessions) >= 2
    stats = fe.drain()
    assert reconciles(stats)
    assert stats["errors"] == 2 and stats["served"] == 1


def test_batch_prefill_failure_closes_session_and_evicts(make_frontend):
    """A prefill failure CLOSES the session (its device state integrity
    is unknown — the DecodeSession contract) and the dispatcher evicts
    it from the warm pool: the failed request answers ERR backend, a
    batchmate already aboard fails with it, and the next request gets
    a FRESH session — a broken session never serves again."""
    sb = faultinject.slot_backend(buckets=(2,), n_new=3,
                                  per_token_s=0.005,
                                  explode_prefill_for={666})
    # queue BEFORE start(): "100" boards first and "666"'s prefill
    # fault kills it in the SAME gathered turn. The TCP-flood version
    # raced arrival order — a fast machine gathered "666" first and
    # alone, so no admission was ever journaled and there was no
    # stepped==0 flush to assert on.
    fe = servd.ServeFrontend(None, slot_backend=sb, batch_max=2,
                             batch_window_ms=50.0, drain_ms=2000.0)
    replies = {}

    def mkreply(i):
        def reply(text):
            replies.setdefault(i, []).append(text)
        return reply

    events = [fe.submit("100", mkreply(0)), fe.submit("666", mkreply(1))]
    fe.start()
    fe.listen(0)
    for ev in events:
        assert ev.wait(20.0), "request never answered"
    resps = [replies[0][-1], replies[1][-1]]
    assert any(r.startswith("ERR backend") for r in resps), resps
    ok = faultinject.serve_request(fe.port, "200", timeout=20.0)
    assert ok == _expect_line(200, 3)
    assert len(sb.sessions) >= 2        # the closed one was evicted
    assert sb.sessions[0].closed
    # the faulted turn's journal flushed under the REAL bucket: the
    # session was already evicted (sess = None) when the flush ran,
    # and a bucket-0 row would poison /batchz and the report's
    # per-bucket table exactly on the fault path being inspected
    flushes = [r for r in fe.batch_flight.list()
               if r.get("stepped") == 0]
    assert flushes and all(r["bucket"] == 2 for r in flushes), flushes
    stats = fe.drain()
    assert reconciles(stats)


def test_batch_prefill_failure_counts_one_breaker_failure(make_frontend):
    """ONE prefill fault in a coalesced batch costs the breaker exactly
    ONE failure count, however many requests die of it: the dispatcher
    stops admitting into the closed session (each further prefill
    would raise and spuriously count again) and answers the rest
    without re-counting — a single fault must not open the circuit."""
    sb = faultinject.slot_backend(buckets=(8,), n_new=3,
                                  explode_prefill_for=set(
                                      range(100, 700, 100)))
    # queue BEFORE start(): all six requests land in ONE gathered batch
    # deterministically, so exactly one prefill fault covers them all
    fe = servd.ServeFrontend(None, slot_backend=sb, batch_max=8,
                             batch_window_ms=0.0, breaker_fails=5,
                             drain_ms=2000.0)
    replies = {}

    def mkreply(i):
        def reply(text):
            replies.setdefault(i, []).append(text)
        return reply

    events = [fe.submit("%d00 7" % (i + 1), mkreply(i))
              for i in range(6)]
    fe.start()
    for ev in events:
        assert ev.wait(10.0), "request never answered"
    assert sorted(replies) == list(range(6))
    for i, texts in replies.items():
        assert len(texts) == 1 and texts[0].startswith("ERR backend"), \
            (i, texts)
    assert fe.breaker.state == "closed", fe.breaker.describe()
    assert fe.breaker.consecutive == 1, fe.breaker.consecutive
    stats = fe.drain()
    assert reconciles(stats) and stats["errors"] == 6


def test_batch_prefill_rejection_never_feeds_breaker(make_frontend):
    """A prefill that raises WITHOUT closing the session (pre-dispatch
    validation — e.g. a too-long prompt against a backend with no
    admits() hook) is a deterministic request defect: answered ERR
    backend, breaker untouched — a flood of client defects must not
    open the circuit and shed healthy traffic."""
    sb = faultinject.slot_backend(buckets=(2,), n_new=3,
                                  reject_for={666})
    fe = make_frontend(None, slot_backend=sb, batch_max=2,
                       batch_window_ms=0.0, breaker_fails=2)
    for _ in range(3):      # more defects than breaker_fails
        bad = faultinject.serve_request(fe.port, "666", timeout=10.0)
        assert bad.startswith("ERR backend"), bad
    assert fe.breaker.state == "closed"
    assert fe.breaker.consecutive == 0
    assert faultinject.serve_request(fe.port, "100",
                                     timeout=10.0) == _expect_line(100, 3)
    stats = fe.drain()
    assert reconciles(stats)
    assert stats["errors"] == 3 and stats["served"] == 1


def test_batch_fresh_batch_occupancy_stamped_batchwide(make_frontend):
    """Members of ONE coalesced fresh batch share their first decode
    pass: every flight record carries the batch occupancy, not the
    sequential admit order (1, 2, ...) — /requestz must not read
    'not coalesced' for the batch's first member."""
    sb = faultinject.slot_backend(buckets=(2,), n_new=3,
                                  per_token_s=0.002)
    # queue BEFORE start(): both requests land in one gathered batch
    fe = servd.ServeFrontend(None, slot_backend=sb, batch_max=2,
                             batch_window_ms=0.0, drain_ms=2000.0)
    done = [fe.submit("%d00 7" % (i + 1), lambda t: None)
            for i in range(2)]
    fe.start()
    for ev in done:
        assert ev.wait(10.0)
    occs = sorted(r["occupancy_at_dispatch"] for r in fe.flight.list())
    assert occs == [2, 2], occs
    stats = fe.drain()
    assert reconciles(stats) and stats["served"] == 2


def test_batch_free_slots_load_signal_in_admin_stats(make_frontend):
    """ADMIN stats reports free decode slots (capacity − active): full
    capacity when idle, reduced while a batch decodes — the router's
    prefer-the-replica-that-can-batch-it-in signal. Solo frontends
    omit the field (backward compatible by absence)."""
    sb = faultinject.slot_backend(buckets=(4,), n_new=20,
                                  per_token_s=0.02)
    fe = make_frontend(None, slot_backend=sb, batch_max=4,
                       batch_window_ms=0.0)

    def stats_field(port, key):
        line = faultinject.serve_request(port, "ADMIN stats",
                                         timeout=5.0)
        kv = dict(p.split("=") for p in line[3:].split())
        return kv.get(key)

    assert stats_field(fe.port, "free_slots") == "4"
    ts = [threading.Thread(
        target=faultinject.serve_request,
        args=(fe.port, "%d00" % (i + 1),), kwargs={"timeout": 30.0})
        for i in range(2)]
    for t in ts:
        t.start()
    time.sleep(0.2)                     # two slots active
    assert stats_field(fe.port, "free_slots") == "2"
    for t in ts:
        t.join()
    solo = make_frontend()              # no slot backend
    line = faultinject.serve_request(solo.port, "ADMIN stats",
                                     timeout=5.0)
    assert "free_slots" not in line


def test_batch_reload_waits_for_inflight_batch(make_frontend):
    """A reload requested mid-batch is deferred until the in-flight
    batch finishes (the slot caches hold the old model's K/V), then
    every warm session is closed and the next request gets a fresh
    session from the reloaded backend."""
    reloads = []
    sb = faultinject.slot_backend(buckets=(2,), n_new=20,
                                  per_token_s=0.01)
    fe = make_frontend(None, slot_backend=sb, batch_max=2,
                       batch_window_ms=0.0, drain_ms=8000.0,
                       reload_fn=lambda: reloads.append(1) or True)
    done = []

    def ask():
        done.append(faultinject.serve_request(fe.port, "100",
                                              timeout=30.0))

    t = threading.Thread(target=ask)
    t.start()
    time.sleep(0.05)                    # batch underway
    assert faultinject.serve_request(
        fe.port, "ADMIN reload", timeout=5.0).startswith("OK")
    assert not reloads                  # deferred: batch still decoding
    t.join()
    assert done[0] == _expect_line(100, 20)
    # the worker honors the flag once the batch drains
    deadline = time.monotonic() + 5.0
    while not reloads and time.monotonic() < deadline:
        time.sleep(0.02)
    assert reloads and sb.closed >= 1
    n_sessions = len(sb.sessions)
    assert faultinject.serve_request(fe.port, "200",
                                     timeout=20.0) == _expect_line(200, 20)
    assert len(sb.sessions) == n_sessions + 1
    stats = fe.drain()
    assert reconciles(stats)


def test_batch_admits_check_answers_err_backend(make_frontend):
    """The slot backend's compatibility check (prompt too long for the
    model) answers a deterministic ERR backend without feeding the
    breaker or poisoning the batch."""
    sb = faultinject.slot_backend(buckets=(2,), n_new=3, max_prompt=3)
    fe = make_frontend(None, slot_backend=sb, batch_max=2)
    bad = faultinject.serve_request(fe.port, "1 2 3 4 5", timeout=10.0)
    assert bad.startswith("ERR backend"), bad
    assert fe.breaker.state == "closed" and fe.breaker.consecutive == 0
    assert faultinject.serve_request(fe.port, "100",
                                     timeout=10.0) == _expect_line(100, 3)
    stats = fe.drain()
    assert reconciles(stats)
    assert stats["errors"] == 1 and stats["served"] == 1


def test_batch_occupancy_metrics_honest_weighted_mean(make_frontend):
    """The occupancy series is a per-iteration account, not a last-write
    gauge: iterations/slot-iterations counters land in telemetry and
    the weighted mean matches the fake backend's journal exactly."""
    reg = telemetry._Registry()
    reg.enable()
    sb = faultinject.slot_backend(buckets=(2,), n_new=4,
                                  per_token_s=0.002)
    orig = telemetry._REG
    telemetry._REG = reg
    try:
        fe = make_frontend(None, slot_backend=sb, batch_max=2,
                           batch_window_ms=40.0)
        resps = faultinject.serve_flood(fe.port, ["100", "200"],
                                        timeout=20.0)
        assert all(r for r in resps)
        fe.drain()
    finally:
        telemetry._REG = orig
    snap = reg.metrics_snapshot()
    iters = snap["counters"]["serve.batch_iterations"]
    slots = snap["counters"]["serve.batch_slot_iterations"]
    assert iters > 0 and slots / float(iters) == fe.mean_occupancy()
    assert fe.mean_occupancy() > 1.0
    # /statusz surfaces the mean (the honest form of the gauge)
    srv = statusd.StatusServer(0, host="127.0.0.1", registry=reg)
    try:
        srv.start()
        page = urlopen("http://127.0.0.1:%d/statusz" % srv.port,
                       timeout=5).read().decode()
        assert "mean occupancy" in page
    finally:
        srv.stop()


# ----------------------------------------------------------------------
# decode-datapath observability (doc/observability.md "Decode datapath"):
# the iteration flight ring, /batchz, the KV account, and the convoy
# detector — all jax-free against faultinject.slot_backend
def test_batch_iteration_flight_ring(make_frontend):
    """Every decode iteration lands in the scheduler flight ring with
    its composition (slot/occupant/age), admissions/retirements, queue
    pressure, and step latency — and the ring's lifetime weighted mean
    IS the serve.batch_iterations counter-pair mean (the regression
    the honest-occupancy contract demands)."""
    reg = telemetry._Registry()
    reg.enable()
    sb = faultinject.slot_backend(buckets=(2,), n_new=4,
                                  per_token_s=0.002)
    orig = telemetry._REG
    telemetry._REG = reg
    try:
        fe = make_frontend(None, slot_backend=sb, batch_max=2,
                           batch_window_ms=40.0)
        resps = faultinject.serve_flood(fe.port, ["100", "200", "300"],
                                        timeout=20.0)
        assert all(not r.startswith("ERR") for r in resps), resps
        recs = fe.batch_flight.list()
        assert recs, "iteration ring empty after a batched flood"
        # ring records carry the full per-iteration schema, and are
        # JSON-serializable (the /batchz?json=1 contract)
        json.dumps(recs)
        for it in recs:
            assert it["bucket"] == 2
            assert 1 <= it["occupancy"] <= 2
            assert it["occupancy"] == len(it["slots"])
            assert it["step_ms"] >= 0
            for slot, rid, age in it["slots"]:
                assert 0 <= slot < 2 and age >= 0
        # every request was admitted and retired through the journal
        ads = [a[0] for it in recs for a in it["admitted"]]
        rets = [r[0] for it in recs for r in it["retired"]]
        served = [r["id"] for r in fe.flight.list()]
        assert sorted(ads) == sorted(rets) == sorted(served)
        # iteration ordinals are dense and newest-first in the listing
        ords = [it["iter"] for it in recs]
        assert ords == sorted(ords, reverse=True)
        # the regression: ring lifetime tallies == the counter pair
        fe.drain()
    finally:
        telemetry._REG = orig
    snap = reg.metrics_snapshot()
    assert fe.batch_flight.iterations \
        == snap["counters"]["serve.batch_iterations"]
    assert fe.batch_flight.slot_iterations \
        == snap["counters"]["serve.batch_slot_iterations"]
    assert fe.batch_flight.mean_occupancy() == fe.mean_occupancy()


def test_batch_flight_records_scheduling_coordinates(make_frontend):
    """Flight records carry bucket / slot / iterations ([first, last]
    step ordinals) next to occupancy_at_dispatch: two coalesced
    requests have overlapping ranges in the same bucket — the
    who-shared-my-decode join /requestz readers use, no ring needed."""
    sb = faultinject.slot_backend(buckets=(2,), n_new=4,
                                  per_token_s=0.002)
    fe = servd.ServeFrontend(None, slot_backend=sb, batch_max=2,
                             batch_window_ms=0.0, drain_ms=2000.0)
    done = [fe.submit("%d00 7" % (i + 1), lambda t: None)
            for i in range(2)]
    fe.start()
    for ev in done:
        assert ev.wait(10.0)
    recs = fe.flight.list()
    assert len(recs) == 2
    for r in recs:
        assert r["bucket"] == 2 and r["slot"] in (0, 1)
        lo, hi = r["iterations"]
        assert 1 <= lo <= hi
    (a_lo, a_hi), (b_lo, b_hi) = (r["iterations"] for r in recs)
    assert max(a_lo, b_lo) <= min(a_hi, b_hi), \
        "coalesced requests must share step iterations"
    assert recs[0]["slot"] != recs[1]["slot"]
    fe.drain()
    # an n_new == 1 request finishes at prefill: it never shares a
    # decode pass, so its iterations field is honestly null — and its
    # admission/retirement still reaches the ring as a NON-stepped
    # flush record (out of the occupancy tallies, never misattributed
    # to a later decode iteration)
    sb1 = faultinject.slot_backend(buckets=(2,), n_new=1)
    fe1 = servd.ServeFrontend(None, slot_backend=sb1, drain_ms=2000.0)
    fe1.start()
    fe1.listen(0)
    assert faultinject.serve_request(fe1.port, "100",
                                     timeout=10.0) == "101"
    assert fe1.flight.list()[0]["iterations"] is None
    deadline = time.monotonic() + 5.0
    while not len(fe1.batch_flight) and time.monotonic() < deadline:
        time.sleep(0.01)
    flush = fe1.batch_flight.list()[0]
    assert flush["stepped"] == 0 and flush["step_ms"] is None
    assert [a[0] for a in flush["admitted"]] == ["1"]
    assert [r[0] for r in flush["retired"]] == ["1"]
    assert fe1.batch_flight.iterations == 0    # no decode pass ran
    fe1.drain()


def test_batchz_endpoint_kv_account_and_decode_metrics(make_frontend):
    """/batchz renders the scheduler ring + KV account (HTML and
    ?json=1), /metrics carries the cxxnet_decode_* families
    Prometheus-valid, and the /metrics?json=1 federation feed carries
    the batch account — against the fake backend's deterministic
    geometry (l_max x kv_row_bytes per slot)."""
    reg = telemetry._Registry()
    reg.enable()
    sb = faultinject.slot_backend(buckets=(2, 4), n_new=30,
                                  per_token_s=0.01, l_max=64,
                                  kv_row_bytes=100)
    orig = telemetry._REG
    telemetry._REG = reg
    srv = None
    try:
        fe = make_frontend(None, slot_backend=sb, batch_max=4,
                           batch_window_ms=0.0, drain_ms=8000.0)
        srv = statusd.StatusServer(0, host="127.0.0.1",
                                   registry=reg).start()
        srv.batch = fe
        srv.flight = fe.flight
        base = "http://127.0.0.1:%d" % srv.port
        ts = [threading.Thread(
            target=faultinject.serve_request,
            args=(fe.port, "%d00" % (i + 1),), kwargs={"timeout": 30.0})
            for i in range(2)]
        for t in ts:
            t.start()
        deadline = time.monotonic() + 5.0
        while fe.batch_flight.iterations < 3 \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        snap = json.loads(urlopen(base + "/batchz?json=1",
                                  timeout=5).read())
        # the fake geometry: one warm 2-slot session, 64 rows x 100
        # bytes per slot; both slots decoding
        assert snap["buckets"]["2"]["warm"] == 1
        assert snap["buckets"]["2"]["kv_bytes"] == 2 * 64 * 100
        assert snap["kv_bytes"] == 2 * 64 * 100
        assert snap["buckets"]["2"]["active"] == 2
        assert snap["kv_live_pct"] is not None \
            and 0 < snap["kv_live_pct"] <= 100
        assert snap["flight"], "ring missing from /batchz?json=1"
        page = urlopen(base + "/batchz", timeout=5).read().decode()
        assert "decode batch scheduler" in page and "buckets" in page
        m = urlopen(base + "/metrics", timeout=5).read().decode()
        for line in m.splitlines():
            if line and not line.startswith("#"):
                assert statusd.PROM_LINE_RE.match(line), line
        assert 'cxxnet_decode_kv_bytes{process="0",bucket="2"} %d' \
            % (2 * 64 * 100) in m
        assert "cxxnet_decode_kv_live_pct" in m
        assert "cxxnet_decode_convoy" in m
        assert "cxxnet_serve_queue_age_seconds_bucket" in m
        feed = json.loads(urlopen(base + "/metrics?json=1",
                                  timeout=5).read())
        assert feed["batch"]["kv_bytes"] == 2 * 64 * 100
        for t in ts:
            t.join()
        fe.drain()
    finally:
        if srv is not None:
            srv.stop()
        telemetry._REG = orig
    # solo processes 404 (the endpoint names its wiring)
    srv2 = statusd.StatusServer(0, host="127.0.0.1").start()
    try:
        urlopen("http://127.0.0.1:%d/batchz" % srv2.port, timeout=5)
        raise AssertionError("/batchz without a frontend should 404")
    except HTTPError as e:
        assert e.code == 404
    finally:
        srv2.stop()


def test_trace_request_merges_slot_gantt_lanes(make_frontend):
    """/trace?request=<id> on a batching replica renders the request's
    scheduler iterations as slot-Gantt lanes: one lane per decode
    slot, bars naming each occupant — the batchmate's id appears in
    the straggler's trace."""
    sb = faultinject.slot_backend(buckets=(2,), n_new=3,
                                  per_token_s=0.005, long_for={100},
                                  long_n_new=12)
    fe = make_frontend(None, slot_backend=sb, batch_max=2,
                       batch_window_ms=40.0, drain_ms=8000.0)
    resps = faultinject.serve_flood(fe.port, ["100", "200"],
                                    timeout=20.0)
    assert all(not r.startswith("ERR") for r in resps), resps
    strag = next(r for r in fe.flight.list()
                 if r["tokens_out"] == 12)
    mate = next(r for r in fe.flight.list() if r["tokens_out"] == 3)
    iters = fe.batch_flight.for_request(strag["id"])
    assert iters and iters == sorted(iters, key=lambda i: i["iter"])
    trace = telemetry.request_chrome_trace(strag, batch_iters=iters)
    lanes = [t["args"]["name"] for t in trace["traceEvents"]
             if t.get("name") == "thread_name"]
    assert any(str(n).startswith("batch slot") for n in lanes), lanes
    bars = [t for t in trace["traceEvents"]
            if t.get("tid", 0) >= 10 and t["ph"] == "X"]
    occupants = {b["args"]["occupant"] for b in bars}
    assert strag["id"] in occupants and mate["id"] in occupants, \
        (occupants, strag["id"], mate["id"])
    # and each bar names the iteration range it covers
    assert all(".." in b["args"]["iterations"] for b in bars)
    fe.drain()


def test_admin_stats_batch_buckets(make_frontend):
    """ADMIN stats reports batch_buckets plus per-bucket warm/active
    counts next to free_slots — the per-bucket load signal routerd
    parses onto /fleetz. Solo frontends omit the whole family."""
    sb = faultinject.slot_backend(buckets=(2, 4), n_new=20,
                                  per_token_s=0.02)
    fe = make_frontend(None, slot_backend=sb, batch_max=4,
                       batch_window_ms=0.0, drain_ms=8000.0)

    def stats(port):
        line = faultinject.serve_request(port, "ADMIN stats",
                                         timeout=5.0)
        return dict(p.split("=") for p in line[3:].split())

    st = stats(fe.port)
    assert st["batch_buckets"] == "2"
    assert st["bucket.2.warm"] == "0" and st["bucket.4.warm"] == "0"
    ts = [threading.Thread(
        target=faultinject.serve_request,
        args=(fe.port, "%d00" % (i + 1),), kwargs={"timeout": 30.0})
        for i in range(2)]
    for t in ts:
        t.start()
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        st = stats(fe.port)
        if st.get("bucket.2.active") == "2":
            break
        time.sleep(0.02)
    assert st["bucket.2.warm"] == "1" and st["bucket.2.active"] == "2"
    assert st["bucket.4.warm"] == "0" and st["bucket.4.active"] == "0"
    for t in ts:
        t.join()
    fe.drain()
    solo = make_frontend()
    line = faultinject.serve_request(solo.port, "ADMIN stats",
                                     timeout=5.0)
    assert "batch_buckets" not in line and "bucket." not in line


def test_convoy_chaos_straggler_pins_bucket(make_frontend):
    """THE convoy acceptance: two stragglers pin a full 2-slot bucket
    while short requests queue at zero free slots — EXACTLY ONE
    decode_convoy latch transition fires (plus its clearing
    transition), the serve.convoys episode counter reads 1, queue-age
    observations land in serve.queue_age, and ZERO requests are lost
    (every one served exactly). Runs under CXXNET_LOCKRANK=1 (the
    suite's autouse fixture)."""
    reg = telemetry._Registry()
    reg.enable()
    sb = faultinject.slot_backend(buckets=(2,), n_new=3,
                                  per_token_s=0.004,
                                  long_for={100, 200}, long_n_new=40)
    orig = telemetry._REG
    telemetry._REG = reg
    try:
        # queue BEFORE start() (the queue-before-start discipline):
        # the stragglers are popped first DETERMINISTICALLY, pin the
        # whole bucket, and the shorts wait behind them — a TCP flood
        # would race arrival order, and shorts served before both
        # stragglers board would leave the queue empty (no convoy)
        fe = servd.ServeFrontend(None, slot_backend=sb, batch_max=2,
                                 batch_window_ms=0.0, convoy_iters=8,
                                 drain_ms=15000.0)
        replies = {}

        def mkreply(i):
            def reply(text):
                replies.setdefault(i, []).append(text)
            return reply

        lines = ["100", "200", "300", "400", "500"]
        events = [fe.submit(line, mkreply(i))
                  for i, line in enumerate(lines)]
        fe.start()
        for ev in events:
            assert ev.wait(40.0), "request never answered"
        for i, texts in sorted(replies.items()):
            assert len(texts) == 1, (i, texts)
        assert replies[0][0] == _expect_line(100, 40)
        assert replies[1][0] == _expect_line(200, 40)
        for i, first in enumerate((300, 400, 500), start=2):
            assert replies[i][0] == _expect_line(first, 3), \
                (i, replies[i])
        fe.drain()
    finally:
        telemetry._REG = orig
    evs = [e for e in reg.events() if e.get("ev") == "decode_convoy"]
    latches = [e for e in evs if e.get("convoy") == 1]
    clears = [e for e in evs if e.get("convoy") == 0]
    assert len(latches) == 1, evs
    assert latches[0]["bucket"] == 2
    assert latches[0]["age_iters"] >= 8
    assert latches[0]["queue_depth"] >= 1
    assert latches[0]["pinned"] in [r["id"] for r in fe.flight.list()]
    # the latch CLEARED when the stragglers retired and the queue
    # drained into the freed slots — a log must not end latched
    assert len(clears) == 1 and clears[0]["episode_iters"] >= 1
    assert fe._convoy is False and fe._convoys == 1
    snap = reg.metrics_snapshot()
    assert snap["counters"]["serve.convoys"] == 1
    # the queue waited at zero free slots: the age histogram saw it
    assert snap["hists"]["serve.queue_age"]["count"] >= 1
    # and the ring marked the convoy iterations
    assert any(it["convoy"] for it in fe.batch_flight.list())
    stats = fe.drain()
    assert reconciles(stats)


def test_batch_snapshot_kv_live_tracks_decode_progress(make_frontend):
    """kv_live_pct measures REAL cache extent: it grows as a sequence
    decodes (more live rows) and collapses to 0 when every slot
    retires (the dead-slot waste paged KV will reclaim) — while
    kv_bytes stays at the warm session's full allocation."""
    sb = faultinject.slot_backend(buckets=(2,), n_new=30,
                                  per_token_s=0.01, l_max=64,
                                  kv_row_bytes=10)
    fe = make_frontend(None, slot_backend=sb, batch_max=2,
                       batch_window_ms=0.0, drain_ms=8000.0)
    t = threading.Thread(target=faultinject.serve_request,
                         args=(fe.port, "100 2 3"),
                         kwargs={"timeout": 30.0})
    t.start()
    deadline = time.monotonic() + 5.0
    first = None
    while time.monotonic() < deadline:
        snap = fe.batch_snapshot()
        if snap["buckets"]["2"]["active"] == 1:
            first = snap
            break
        time.sleep(0.005)
    assert first is not None, "sequence never observed mid-decode"
    t.join()
    # drained: the warm allocation persists, the live share is gone
    deadline = time.monotonic() + 5.0
    while fe.batch_snapshot()["buckets"]["2"]["active"] \
            and time.monotonic() < deadline:
        time.sleep(0.01)
    after = fe.batch_snapshot()
    assert after["kv_bytes"] == first["kv_bytes"] == 2 * 64 * 10
    assert after["kv_live_bytes"] == 0 and after["kv_live_pct"] == 0.0
    assert after["slot_waste_pct"] == 100.0
    assert first["kv_live_bytes"] > 0
    assert fe.decode_kv_bytes() == 2 * 64 * 10
    # drain closes the warm sessions and ZEROES the account: a scrape
    # during the shutdown window (or a later task reading the perf
    # ledger's decode hook) must never see freed memory as allocated
    fe.drain()
    deadline = time.monotonic() + 5.0
    while fe.decode_kv_bytes() and time.monotonic() < deadline:
        time.sleep(0.01)
    assert fe.decode_kv_bytes() == 0
    assert fe.batch_snapshot()["kv_bytes"] == 0


def test_report_batch_scheduler_section(tmp_path, capsys):
    """telemetry_report's batch-scheduler section: per-bucket weighted
    occupancy reconstructed from the transition-only batch_iteration
    events (composition holds constant across the gap to the next
    event — gap-weighting is exact), waste vs the bucket size,
    admission-latency percentiles, and the convoy episode account —
    with the log-ends-latched unresolved flag."""
    evs = [
        {"ev": "meta", "pid": 1, "t0_wall": 0.0},
        {"ev": "batch_iteration", "iter": 1, "bucket": 4,
         "occupancy": 2, "occupancy_after": 2, "queue_depth": 0,
         "step_ms": 3.0, "admitted": ["1", "2"], "retired": [],
         "ts": 1.0},
        {"ev": "batch_iteration", "iter": 5, "bucket": 4,
         "occupancy": 4, "occupancy_after": 4, "queue_depth": 2,
         "step_ms": 3.0, "admitted": ["3", "4"], "retired": [],
         "ts": 2.0},
        # iteration 9 stepped 3 sequences and retired one: occupancy
        # (what decoded) and occupancy_after (what is left) differ —
        # the post-retirement gap must weigh at the AFTER composition
        {"ev": "batch_iteration", "iter": 9, "bucket": 4,
         "occupancy": 3, "occupancy_after": 2, "queue_depth": 0,
         "step_ms": 3.0, "admitted": [], "retired": ["1"], "ts": 3.0},
        # a non-stepped flush (an n_new==1 admission that finished at
        # prefill): journaled, but NOT a decode iteration
        {"ev": "batch_iteration", "iter": 9, "bucket": 4,
         "occupancy": 0, "occupancy_after": 0, "stepped": 0,
         "queue_depth": 0, "step_ms": None, "admitted": ["9"],
         "retired": ["9"], "ts": 3.5},
        {"ev": "decode_convoy", "convoy": 1, "bucket": 4,
         "pinned": "2", "slot": 1, "age_iters": 70,
         "queue_depth": 3, "ts": 4.0},
        {"ev": "serve_request_done", "req": "1", "outcome": "served",
         "tokens": 4, "total_s": 0.1, "queue_wait_s": 0.02,
         "dispatch_s": 0.001, "prefill_s": 0.01, "decode_s": 0.05,
         "recompiles": 0, "ts": 5.0},
    ]
    log = tmp_path / "batch.jsonl"
    log.write_text("".join(json.dumps(e) + "\n" for e in evs))
    rc = telemetry_report.main([str(log), "--json"])
    agg = json.loads(capsys.readouterr().out)
    assert rc == 0
    bt = agg["batch"]
    # exact reconstruction: iter 1 at occ 2 + iters 2..4 at after 2
    # (8), iter 5 at 4 + 6..8 at 4 (16), iter 9 at 3 (3) -> 9
    # iterations, 27 slot-iterations, mean 3.0; the flush event adds
    # its admitted/retired counts but NO iterations
    b4 = bt["buckets"]["4"]
    assert b4["iterations"] == 9
    assert b4["slot_iterations"] == 27
    assert b4["mean_occupancy"] == 3.0
    assert b4["waste_pct"] == 25.0
    assert b4["admitted"] == 5 and b4["retired"] == 2
    assert bt["admission_p99_ms"] == 20.0
    assert bt["convoy_episodes"] == 1
    # the log ENDS with the convoy latched: flagged unresolved
    assert bt["convoy_unresolved"] == ["0"]
    rc = telemetry_report.main([str(log)])
    out = capsys.readouterr().out
    assert rc == 0 and "== batch scheduler" in out
    assert "convoy episodes: 1" in out and "UNRESOLVED" in out
    assert "pinned=2" in out


# ----------------------------------------------------------------------
# ----------------------------------------------------------------------
# multi-tenant weighted-fair QoS (doc/serving.md "Multi-tenant QoS")
TEN = "noisy:1,victim:4"


def park_worker_and_fill(fe, port, tenant, n, first="9"):
    """Occupy the worker with one request, then queue ``n`` more from
    ``tenant`` — deterministically (the occupy_and_fill discipline:
    waiting on counters alone races the worker's pop)."""
    socks = []
    s = socket.create_connection(("127.0.0.1", port), timeout=5)
    s.sendall(("TENANT %s %s\n" % (tenant, first)).encode())
    socks.append(s)
    deadline = time.monotonic() + 5.0
    while not fe._inflight and time.monotonic() < deadline:
        time.sleep(0.005)
    assert fe._inflight, "worker never occupied"
    for i in range(n):
        s = socket.create_connection(("127.0.0.1", port), timeout=5)
        s.sendall(("TENANT %s %d\n" % (tenant, 10 + i)).encode())
        socks.append(s)
        want = i + 1
        deadline = time.monotonic() + 5.0
        while len(fe._q) < want and time.monotonic() < deadline:
            time.sleep(0.005)
        assert len(fe._q) == want, "queue fill stalled at %d" % len(fe._q)
    return socks


def test_tenant_prefix_parse_validation_and_compat(make_frontend):
    """The TENANT wire contract: adopted + accounted, composes with
    TRACE and DEADLINE (TRACE first), malformed/unknown ids are ERR
    proto (deterministic, never dispatched), and prefix-less clients
    ride the default tenant unchanged — the downgrade acceptance."""
    fe = make_frontend(tenants=TEN, tenant_default="victim")
    port = fe.port
    assert faultinject.serve_request(port, "TENANT noisy 1 2") == "2 3"
    assert faultinject.serve_request(
        port, "TRACE t-1 TENANT noisy DEADLINE 5000 7") == "8"
    assert fe.flight.get("t-1")["tenant"] == "noisy"
    # prefix-less clients are the default tenant — wire unchanged
    assert faultinject.serve_request(port, "5") == "6"
    assert fe.flight.list()[0]["tenant"] == "victim"
    for bad in ("TENANT", "TENANT bad!id 1", "TENANT %s 1" % ("x" * 33),
                "TENANT ghost 1"):
        resp = faultinject.serve_request(port, bad)
        assert resp.startswith("ERR proto tenant"), (bad, resp)
    assert faultinject.serve_request(
        port, "TENANT noisy").startswith("ERR empty")
    # TENANT + ADMIN composes (prefixes stripped first); the stats line
    # carries the per-tenant books
    resp = faultinject.serve_request(port, "TENANT noisy ADMIN stats")
    assert resp.startswith("OK ")
    assert "tenant.noisy.accepted=" in resp
    assert "tenant.victim.served=" in resp
    ts = fe.tenant_stats()
    assert ts["noisy"]["accepted"] == 2 and ts["noisy"]["served"] == 2
    assert ts["victim"]["accepted"] == 1
    stats = fe.drain()
    assert reconciles(stats)
    for t, st in fe.tenant_stats().items():
        assert st["accepted"] == (st["served"] + st["errors"]
                                  + st["shed"] + st["deadline"]), (t, st)


def test_tenant_fair_share_shed_and_eviction(make_frontend):
    """The capacity-fairness contract: a borrower over its fair share
    is shed with the ``tenant`` detail token (NOT retryable — the
    policy holds fleet-wide), and an under-share arrival EVICTS the
    borrower's newest queued request instead of being shed itself."""
    from cxxnet_tpu.utils import routerd
    release = threading.Event()

    def slow(toks, seq):
        release.wait(10.0)
        return [t + 1 for t in toks]

    fe = make_frontend(slow, queue_size=4, tenants=TEN,
                       tenant_default="victim")
    port = fe.port
    socks = park_worker_and_fill(fe, port, "noisy", 4)
    try:
        assert fe._q.shares == {"noisy": 1, "victim": 3}
        # noisy is over its share of a full queue: its arrival sheds
        # with the machine-readable "tenant" verdict, which the router
        # must NOT retry (every replica shares the table)
        resp = faultinject.serve_request(port, "TENANT noisy 99")
        assert resp.startswith("ERR busy tenant"), resp
        assert not routerd.retryable(resp)
        assert fe.flight.list()[0]["shed_at"] == "tenant"
        # a victim arrival is UNDER its share: admitted by evicting the
        # borrower's newest queued request (charged to noisy)
        got = []
        done = fe.submit("TENANT victim 50", got.append)
        assert done is not None, "victim was shed instead of admitted"
        assert len(fe._q) == 4 and fe._q.depth("victim") == 1
        ts = fe.tenant_stats()
        assert ts["noisy"]["shed"] == 2      # the arrival + the evictee
        assert ts["victim"]["shed"] == 0
        release.set()
        done.wait(5.0)
        assert got == ["51"]
    finally:
        release.set()
        stats = fe.drain()
        for s in socks:
            s.close()
    assert reconciles(stats)
    for t, st in fe.tenant_stats().items():
        assert st["accepted"] == (st["served"] + st["errors"]
                                  + st["shed"] + st["deadline"]), (t, st)


def test_tenant_weighted_fair_scheduling_order(make_frontend):
    """The stride scheduler: with both tenants backlogged, a weight-4
    tenant gets 4 dispatches for every 1 of a weight-1 tenant — the
    worker pop order interleaves by weight, not arrival order."""
    order = []
    release = threading.Event()

    def recording(toks, seq):
        release.wait(10.0)
        order.append(toks[0])
        return [t + 1 for t in toks]

    fe = make_frontend(recording, queue_size=16, tenants=TEN,
                       tenant_default="victim")
    port = fe.port
    # park the worker, then queue noisy FIRST (arrival order would
    # serve all noisy before any victim)
    socks = park_worker_and_fill(fe, port, "noisy", 4)
    try:
        for i in range(4):
            s = socket.create_connection(("127.0.0.1", port), timeout=5)
            s.sendall(("TENANT victim %d\n" % (20 + i)).encode())
            socks.append(s)
        deadline = time.monotonic() + 5.0
        while len(fe._q) < 8 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert len(fe._q) == 8
        release.set()
        deadline = time.monotonic() + 5.0
        while len(order) < 9 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(order) == 9, order
        # order[0] is the parked noisy request (mid-dispatch when the
        # backlog formed); among the next 5 pops at least 4 are victim
        # (weight 4 vs 1), all queued AFTER every noisy request
        victims = [t for t in order[1:6] if t >= 20]
        assert len(victims) >= 4, order
    finally:
        release.set()
        fe.drain()
        for s in socks:
            s.close()


def test_tenant_slo_isolation(make_frontend):
    """A noisy tenant's sheds burn the NOISY error budget; the victim's
    own tracker holds at 0 — per-tenant SLO floors from the existing
    SLOTracker, per tenant."""
    release = threading.Event()

    def slow(toks, seq):
        release.wait(10.0)
        return list(toks)

    slo_t = {t: statusd.SLOTracker(availability=0.999, min_requests=3,
                                   min_bad=3, window_s=60.0)
             for t in ("noisy", "victim")}
    fe = make_frontend(slow, queue_size=2, tenants=TEN,
                       tenant_default="victim", slo_tenants=slo_t)
    port = fe.port
    # worker parked on noisy, queue FULL of noisy borrowings
    socks = park_worker_and_fill(fe, port, "noisy", 2)
    try:
        for _ in range(3):
            # every further noisy arrival is over-share on a full
            # queue: shed, charged to noisy's own error budget
            resp = faultinject.serve_request(port, "TENANT noisy 7")
            assert resp.startswith("ERR busy tenant"), resp
        assert slo_t["noisy"].snapshot()["alert"] == 1
        assert slo_t["victim"].snapshot()["alert"] == 0
    finally:
        release.set()
        fe.drain()
        for s in socks:
            s.close()


def test_servd_selftest():
    assert servd.selftest() == 0


# -- paged KV block pool: exhaustion is a deterministic queue-wait ----
# (doc/performance.md "Decode KV cache"; CXXNET_LOCKRANK=1 via the
# suite's autouse fixture — the admission gate reads the allocator
# outside servd's locks, and these chaos floods prove no inversion)


def test_paged_kv_exhaustion_deterministic_queue_wait(make_frontend):
    """THE pool-exhaustion acceptance: a flood whose sequences need 2
    blocks each over a 4-block pool can run at most TWO concurrent
    sequences however many slots the bucket has — the gather gate
    defers the rest in FIFO order (deterministic queue-wait: zero
    lost, zero errors, zero device faults, not one KVPoolExhausted
    raised), retirements return blocks mid-decode and the queue
    drains into them, and the /batchz + ADMIN stats + flight-ring
    block columns publish the pressure."""
    sb = faultinject.slot_backend(buckets=(4,), n_new=4,
                                  per_token_s=0.002,
                                  kv_pool_blocks=4, kv_block_tokens=4)
    fe = make_frontend(None, slot_backend=sb, batch_max=4,
                       batch_window_ms=0.0, drain_ms=15000.0)
    lines = ["%d %d %d %d" % (10 * i, 10 * i + 1, 10 * i + 2,
                              10 * i + 3) for i in range(1, 9)]
    resps = faultinject.serve_flood(fe.port, lines, timeout=30.0)
    for i, r in enumerate(resps):
        assert r == _expect_line(10 * (i + 1), 4), (i, r)
    # the gate made exhaustion unreachable: the allocator never even
    # SAW an over-ask (admissions deferred in the queue instead)
    assert sb.alloc.alloc_failures == 0
    assert sb.alloc.free_blocks == sb.alloc.usable
    sb.alloc.check()
    # never more concurrent sequences than the pool covers: every
    # iteration record's occupancy respects the BLOCK bound (2), not
    # the slot bound (4), and the ring carries the block columns
    recs = fe.batch_flight.list()
    assert recs
    for r in recs:
        assert r["occupancy"] <= 2, r
        assert r["blocks_total"] == 4 and 0 <= r["blocks_free"] <= 4
    snap = fe.batch_snapshot()
    assert snap["pool"]["blocks_total"] == 4
    assert snap["pool"]["blocks_free"] == 4
    st = dict(kv.split("=") for kv in faultinject.serve_request(
        fe.port, "ADMIN stats", timeout=5.0).split()[1:])
    assert st["kv_blocks_total"] == "4"
    assert st["kv_blocks_free"] == "4"
    stats = fe.drain()
    assert reconciles(stats)
    assert stats["accepted"] == stats["served"] == 8


def test_paged_kv_exhaustion_requeue_path(make_frontend):
    """With the gather-budget hooks disarmed (a backend that cannot
    predict demand), admission reaches the allocator and raises
    KVPoolExhausted — the dispatcher must REQUEUE to the head (a
    deterministic retry after the next retirement), never answer ERR,
    never count a breaker failure, and still serve every request
    exactly."""
    sb = faultinject.slot_backend(buckets=(4,), n_new=4,
                                  per_token_s=0.002,
                                  kv_pool_blocks=4, kv_block_tokens=4,
                                  kv_gate=False)
    fe = make_frontend(None, slot_backend=sb, batch_max=4,
                       batch_window_ms=0.0, drain_ms=15000.0)
    lines = ["%d %d %d %d" % (10 * i, 10 * i + 1, 10 * i + 2,
                              10 * i + 3) for i in range(1, 7)]
    resps = faultinject.serve_flood(fe.port, lines, timeout=30.0)
    for i, r in enumerate(resps):
        assert r == _expect_line(10 * (i + 1), 4), (i, r)
    # the allocator DID refuse some admissions (the path under test)…
    assert sb.alloc.alloc_failures > 0
    # …and every refusal became a requeue: no error class, no breaker
    # count, no session closed mid-serve, nothing lost
    assert sb.closed == 0
    stats = fe.drain()
    assert reconciles(stats)
    assert stats["accepted"] == stats["served"] == 6
    assert stats["errors"] == 0 and stats["shed"] == 0
    assert sb.alloc.free_blocks == sb.alloc.usable
    sb.alloc.check()


def test_paged_kv_tenant_fair_queue_gate_and_requeue(make_frontend):
    """Paged KV composes with the PR 12 tenant fair queue: the gather
    gate budgets the queue's ``peek()`` (the virtual-time head — the
    fair queue is not subscriptable), and the defer path requeues
    through its ``appendleft`` (tenant-head insert, stride refunded).
    Both paths flood two tenants over a pool that can hold only two
    concurrent sequences: every request serves exactly, zero errors,
    zero lost, the worker survives, the pool drains back to full."""
    for gate in (True, False):
        sb = faultinject.slot_backend(buckets=(4,), n_new=4,
                                      per_token_s=0.002,
                                      kv_pool_blocks=4,
                                      kv_block_tokens=4, kv_gate=gate)
        fe = make_frontend(None, slot_backend=sb, batch_max=4,
                           batch_window_ms=0.0, drain_ms=15000.0,
                           tenants=TEN, tenant_default="victim")
        lines = ["TENANT %s %d %d %d %d"
                 % (("noisy", "victim")[i % 2], 10 * i, 10 * i + 1,
                    10 * i + 2, 10 * i + 3) for i in range(1, 7)]
        resps = faultinject.serve_flood(fe.port, lines, timeout=30.0)
        for i, r in enumerate(resps):
            assert r == _expect_line(10 * (i + 1), 4), (gate, i, r)
        if gate:
            # the budgeted gather never over-admits: the allocator
            # never saw an over-ask even through the fair queue's
            # virtual-time pop order
            assert sb.alloc.alloc_failures == 0
        else:
            # the allocator DID refuse — every refusal requeued via
            # _FairQueue.appendleft (the pre-fix AttributeError path)
            assert sb.alloc.alloc_failures > 0
        assert sb.closed == 0
        stats = fe.drain()
        assert reconciles(stats)
        assert stats["accepted"] == stats["served"] == 6
        assert stats["errors"] == 0 and stats["shed"] == 0
        assert sb.alloc.free_blocks == sb.alloc.usable
        sb.alloc.check()


# -- retained conversation cache: never-OOM memory governance ---------
# (doc/robustness.md "Memory governance"; PR 18. CXXNET_LOCKRANK=1 via
# the autouse fixture — eviction runs under the rank-15 kvblocks.evict
# lock inside the admission path, and these floods prove no inversion)


def _books_reconcile(alloc):
    """The retained invariant, asserted at a quiescent instant: every
    block is live, retained, or free — and nothing else."""
    assert (alloc.live_blocks + alloc.retained_blocks
            + alloc.free_blocks) == alloc.usable
    alloc.check()


def _laws_hold():
    """Sweep the process-global conservation-law auditor and assert no
    serving law latched: the chaos ran with the books provably
    balanced. A latch is sticky, so a single mid-storm violation
    anywhere in the flood fails here even if the books reconcile again
    by the time the assert runs."""
    telemetry.audit_sweep()
    broken = telemetry.auditor().snapshot()["broken"]
    assert not set(broken) & {"serve.books", "serve.tenant_books",
                              "kv.blocks"}, broken


def test_retained_kv_exhaustion_chaos_flood(make_frontend):
    """THE never-OOM acceptance: mixed multi-turn + one-shot traffic
    floods a pool far too small to hold every conversation's cache.
    Turn N+1 of each conversation extends turn N's prompt (the
    retained-revival path: refcount 0 -> 1), one-shot noise churns the
    retained pool through LRU eviction, and true exhaustion (live
    blocks alone exceeding the pool) still defers deterministically.
    Invariants: zero OOM (no KVPoolExhausted escapes — the gate +
    evict-before-defer absorb everything), zero deadlock (the flood
    completes under CXXNET_LOCKRANK=1), zero silent losses (every
    request answered exactly once, token-exact — an evicted-then-
    revived conversation recomputes, never serves stale KV), and the
    books reconcile: live + retained + free == pool, always."""
    sb = faultinject.slot_backend(buckets=(4,), n_new=4,
                                  per_token_s=0.002,
                                  kv_pool_blocks=12, kv_block_tokens=4,
                                  kv_retained_frac=1.0)
    fe = make_frontend(None, slot_backend=sb, batch_max=4,
                       batch_window_ms=0.0, drain_ms=15000.0)
    results = {}

    def convo_client(c):
        # a live multi-turn client: turn k+1 is sent the moment turn
        # k answers, its prompt one block longer — the just-retired
        # chain is the NEWEST retained mass, so LRU eviction recycles
        # the noise first and the head of a chain last (leaf-first
        # eviction order): revival is what the design promises here
        out = []
        for turn in range(3):
            p = list(range(100 * c + 1, 100 * c + 5 + 4 * turn))
            line = " ".join(map(str, p))
            out.append((line, faultinject.serve_request(
                fe.port, line, timeout=60.0)))
        results["convo%d" % c] = out

    def noise_client(z):
        # one-shot churn: distinct prompts that only ever park and
        # get evicted — the traffic that would OOM an unguarded pool
        out = []
        for i in range(3):
            t0 = 1000 * z + 10 * i + 1
            line = " ".join(str(t0 + k) for k in range(4))
            out.append((line, faultinject.serve_request(
                fe.port, line, timeout=60.0)))
        results["noise%d" % z] = out

    clients = [threading.Thread(target=convo_client, args=(c,))
               for c in (1, 2, 3)]
    clients += [threading.Thread(target=noise_client, args=(z,))
                for z in (1, 2)]
    for t in clients:
        t.start()
    # the conservation-law auditor sweeps CONTINUOUSLY through the
    # chaos (ISSUE 19 acceptance: books_broken never latches under the
    # eviction storm) — a mid-flight inconsistency a law cannot prove
    # persistent stays inconclusive by design, so any latch IS real
    deadline = time.monotonic() + 120.0
    while any(t.is_alive() for t in clients):
        telemetry.audit_sweep()
        for t in clients:
            t.join(0.05)
        assert time.monotonic() < deadline, \
            "chaos client wedged (deadlock?)"
    _laws_hold()
    for name, out in sorted(results.items()):
        for line, r in out:
            t0 = int(line.split()[0])
            assert r == _expect_line(t0, 4), (name, line, r)
    # the chaos DID exercise the governance, not a comfortable pool:
    # conversations revived retained blocks AND the one-shot churn
    # forced retained evictions
    assert sb.alloc.retained_hits > 0
    assert sb.alloc.retained_hit_tokens > 0
    assert sb.alloc.retained_evictions > 0
    # zero OOM, zero device faults, zero silent losses
    assert sb.closed == 0
    stats = fe.drain()
    assert reconciles(stats)
    assert stats["accepted"] == stats["served"] == 3 * 3 + 2 * 3
    assert stats["errors"] == 0 and stats["shed"] == 0
    # quiescent books: nothing live, everything parked or free
    assert sb.alloc.live_blocks == 0
    assert sb.alloc.available_blocks == sb.alloc.usable
    sb.alloc.check()


def test_retained_eviction_storm_and_revive_race(make_frontend):
    """The chaos knobs: an eviction storm drains the WHOLE retained
    pool between a gather-time match and its admission, and the
    revive-race knob evicts the LRU leaf before every admission — the
    block a request hoped to revive is exactly the one recycled.
    Admissions must recompute instead of crash, replies stay
    token-exact, and the books reconcile after every round."""
    for knobs in ({"kv_evict_storm": 3}, {"kv_revive_race": True},
                  {"kv_evict_storm": 2, "kv_revive_race": True}):
        sb = faultinject.slot_backend(buckets=(4,), n_new=4,
                                      per_token_s=0.002,
                                      kv_pool_blocks=8,
                                      kv_block_tokens=4,
                                      kv_retained_frac=1.0, **knobs)
        fe = make_frontend(None, slot_backend=sb, batch_max=4,
                           batch_window_ms=0.0, drain_ms=15000.0)
        base = list(range(1, 5))
        for turn in range(3):
            # two conversations re-serving the SAME growing prompt +
            # one-shot churn: every admission races the eviction knobs
            lines = [" ".join(map(str, base + list(range(5, 5 + 4 * turn)))),
                     " ".join(map(str, base + list(range(50, 54)))),
                     " ".join(str(9000 + 100 * turn + k)
                              for k in range(4))]
            resps = faultinject.serve_flood(fe.port, lines,
                                            timeout=60.0)
            for line, r in zip(lines, resps):
                t0 = int(line.split()[0])
                assert r == _expect_line(t0, 4), (knobs, turn, line, r)
            _books_reconcile(sb.alloc)
            _laws_hold()        # no conservation law latched mid-storm
        assert sb.closed == 0
        stats = fe.drain()
        assert reconciles(stats)
        assert stats["errors"] == 0 and stats["shed"] == 0
        assert stats["accepted"] == stats["served"] == 9
        assert sb.alloc.live_blocks == 0
        sb.alloc.check()


def test_evict_before_defer_admission(make_frontend):
    """A reservation that the free list cannot cover but free +
    retained CAN must evict and admit — never defer. Sequential
    one-shots fill the retained pool to the brim; a second wave of
    distinct prompts then admits by recycling it: zero alloc_failures
    (the allocator never refused), retained_evictions > 0 (the
    funding), every reply exact."""
    sb = faultinject.slot_backend(buckets=(1,), n_new=4,
                                  kv_pool_blocks=4, kv_block_tokens=4,
                                  kv_retained_frac=1.0)
    fe = make_frontend(None, slot_backend=sb, batch_max=1,
                       batch_window_ms=0.0, drain_ms=15000.0)
    # wave 1: fill retention (each request: 1 registered block parks
    # at retire, 1 scratch block frees) until the cap (4) is reached
    for i in range(1, 5):
        t0 = 10 * i
        line = " ".join(str(t0 + k) for k in range(4))
        assert faultinject.serve_request(fe.port, line,
                                         timeout=30.0) \
            == _expect_line(t0, 4)
    assert sb.alloc.retained_blocks > 0
    retained_before = sb.alloc.retained_blocks
    # wave 2: distinct prompts over a free list too small for them —
    # funded by eviction, not deferred into the queue forever
    for i in range(5, 9):
        t0 = 10 * i
        line = " ".join(str(t0 + k) for k in range(4))
        assert faultinject.serve_request(fe.port, line,
                                         timeout=30.0) \
            == _expect_line(t0, 4)
    assert sb.alloc.alloc_failures == 0
    assert sb.alloc.retained_evictions > 0
    assert sb.alloc.retained_blocks <= sb.alloc.retained_cap
    stats = fe.drain()
    assert reconciles(stats)
    assert stats["accepted"] == stats["served"] == 8
    _books_reconcile(sb.alloc)


def test_kv_pressure_latch_sheds_retained(make_frontend):
    """The low-headroom pressure latch: when the free list drops under
    kv_pressure_pct percent of the pool, the worker latches
    cxxnet_decode_kv_pressure, sheds retained blocks toward the clear
    threshold through the backend's kv_shed_retained hook, emits ONE
    kv_pressure transition event per edge (hysteresis — no flapping),
    and publishes the latch through /batchz, ADMIN stats and the
    federation feed."""
    sb = faultinject.slot_backend(buckets=(1,), n_new=4,
                                  kv_pool_blocks=8, kv_block_tokens=4,
                                  kv_retained_frac=1.0)
    fe = make_frontend(None, slot_backend=sb, batch_max=1,
                       batch_window_ms=0.0, drain_ms=15000.0,
                       kv_pressure_pct=50.0,
                       kv_pressure_clear_pct=75.0)
    # distinct one-shots park one retained block each: free drops 8 ->
    # 7 -> 6 -> 5 -> 3 (under 50%) -> latch fires, sheds back to >= 6
    for i in range(1, 8):
        t0 = 10 * i
        line = " ".join(str(t0 + k) for k in range(4))
        assert faultinject.serve_request(fe.port, line,
                                         timeout=30.0) \
            == _expect_line(t0, 4)
    assert fe._kv_pressures >= 1
    assert fe._kv_shed_blocks > 0
    assert sb.alloc.retained_evictions > 0
    # hysteresis: after the shed the latch CLEARED (free >= clear_pct)
    snap = fe.batch_snapshot()
    assert snap["pool"]["pressure"] == 0
    assert snap["pool"]["blocks_free"] >= 6
    # the retained sub-fields ride the snapshot for /batchz + bench
    assert "retained_hit_rate" in snap["pool"]
    assert "kv_retained_pct" in snap["pool"]
    # ADMIN stats carries the governance keys (what routerd federates)
    st = dict(kv.split("=") for kv in faultinject.serve_request(
        fe.port, "ADMIN stats", timeout=5.0).split()[1:])
    assert "kv_retained_blocks" in st and "kv_retained_hits" in st
    assert st["kv_pressure"] == "0"
    stats = fe.drain()
    assert reconciles(stats)
    assert stats["accepted"] == stats["served"] == 7
    _books_reconcile(sb.alloc)


# -- request autopsy on a live flood (utils/autopsy.py; ISSUE 19) -----
def test_autopsy_warm_flood_zero_compile_stall(make_frontend):
    """The autopsy acceptance on a live flood: requests riding the
    warm-up pay the compile cliff (compile_stall > 0 on their
    verdicts), and a warm-bucket flood afterwards attributes EXACTLY
    zero seconds to compile_stall — the classifier must not smear the
    cliff onto requests that rode warm programs. Every verdict tiles
    >= 95% of the request's wall clock."""
    sb = faultinject.slot_backend(buckets=(4,), n_new=4,
                                  per_token_s=0.001, compile_ms=40.0)
    fe = make_frontend(None, slot_backend=sb, batch_max=4,
                       batch_window_ms=0.0, drain_ms=15000.0)
    # warm-up: the first request compiles session + prefill + step
    assert faultinject.serve_request(fe.port, "1 2 3 4",
                                     timeout=30.0) == _expect_line(1, 4)
    warm_rec = fe.flight.list()[0]
    assert warm_rec["compile_stall_s"] > 0
    aut = warm_rec["autopsy"]
    assert aut["causes"]["compile_stall"] > 0
    assert sum(aut["causes"].values()) >= 0.95 * aut["wall_s"] > 0
    # warm flood: the same prompt shape on the warm bucket — the jit-
    # cache twin has seen every key, so zero stall, zero smearing
    lines = [" ".join(str(10 * i + k) for k in range(4))
             for i in range(2, 8)]
    resps = faultinject.serve_flood(fe.port, lines, timeout=30.0)
    for line, r in zip(lines, resps):
        assert r == _expect_line(int(line.split()[0]), 4), (line, r)
    recs = [r for r in fe.flight.list() if r["id"] != warm_rec["id"]]
    assert len(recs) == len(lines)
    for rec in recs:
        aut = rec["autopsy"]
        assert rec["compile_stall_s"] == 0.0
        assert aut["causes"]["compile_stall"] == 0.0       # exactly 0
        assert aut["primary"] != "compile_stall"
        assert sum(aut["causes"].values()) >= 0.95 * aut["wall_s"] > 0
    stats = fe.drain()
    assert reconciles(stats)
    assert stats["accepted"] == stats["served"] == 7
