"""The example/transformer LM: DSL-built causal transformer learns a
deterministic grammar (exercises embed/attention/add/conv-FFN/seq-softmax
end to end, incl. the softmax seq=1 loss)."""

import os
import pytest
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                "..", "example", "transformer"))

import train_lm  # noqa: E402


def test_lm_learns_grammar():
    from cxxnet_tpu.nnet.trainer import Trainer
    from cxxnet_tpu.utils.config import ConfigIterator
    conf = os.path.join(os.path.dirname(__file__), "..",
                        "example", "transformer", "lm.conf")
    tr = Trainer()
    for k, v in ConfigIterator(conf, ["dev=cpu"]):
        tr.set_param(k, v)
    tr.init_model()
    rs = np.random.RandomState(0)
    eval_b = train_lm.make_batch(np.random.RandomState(999))
    before = train_lm.next_token_accuracy(tr, eval_b)
    assert before < 0.2, "untrained accuracy should be near chance"
    for _ in range(120):
        tr.update(train_lm.make_batch(rs))
    after = train_lm.next_token_accuracy(tr, eval_b)
    assert after > 0.7, "LM failed to learn the grammar: %.3f" % after


@pytest.mark.slow
def test_lm_pipeline_conf_learns_grammar():
    """lm_pipeline.conf: the composed pp x tp x dp + ZeRO-1 example
    trains the same grammar through the example driver."""
    acc = train_lm.main(steps=120, conf_name="lm_pipeline.conf")
    assert acc > 0.7, "composed-mesh LM accuracy %.3f" % acc


@pytest.mark.slow
def test_serve_lm_demo_agrees_across_surfaces():
    """example/transformer/serve_lm.py: in-process generate, the
    exported prefill/step artifact loop, and tensor-parallel serving
    produce identical tokens (run short — agreement holds at any
    training step). Slow tier (tier-1 budget): the per-surface
    token-exactness is pinned in tier-1 by test_decode/test_export;
    this adds the cross-surface demo agreement."""
    import subprocess
    env = dict(os.environ, CXXNET_JAX_PLATFORM="cpu")
    p = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "..", "example",
                      "transformer", "serve_lm.py"), "25"],
        capture_output=True, text=True, timeout=900, env=env,
        cwd=os.path.join(os.path.dirname(__file__), "..", "example",
                         "transformer"))
    assert p.returncode == 0, (p.stdout[-1500:], p.stderr[-1500:])
    assert "SERVING DEMO PASSED" in p.stdout
    assert "artifact decode loop: MATCH" in p.stdout
    assert "tensor-parallel serving (mp=2): MATCH" in p.stdout
