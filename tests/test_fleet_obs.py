"""Fleet observability plane (ISSUE 10): cross-process trace
propagation through the router, stitched Chrome traces, live fleet
metrics federation with EXACT histogram merge, fleet-wide SLO burn,
and outlier-replica detection.

Everything here is jax-free and in-process (servd frontends + statusd
servers on loopback, routers with probing and federation OFF the clock
— every sweep is an explicit call), so the suite stays cheap; the
subprocess chaos lives in test_routerd.py.

The headline guarantees:

* ONE id names a request on every process that touched it — including
  a replica that only SHED it (the retried-request case);
* router ``/trace?request=<id>`` returns one stitched trace whose
  router lane and every replica phase lane share the id, clock-aligned
  on the wall epoch;
* pre-TRACE replicas and TRACE-less clients keep working unchanged
  (the backward-compat acceptance);
* fleet histogram federation is exact: merged bucket counts equal the
  sum of per-replica bucket counts;
* the fleet SLO account fires on a fleet-wide budget violation no
  single replica triggers alone.
"""

import json
import os
import socket
import threading
import time
from urllib.request import urlopen

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from cxxnet_tpu.utils import routerd, servd, statusd, telemetry

from . import faultinject


@pytest.fixture(autouse=True)
def _lockrank_on(monkeypatch):
    """Runtime lock-order enforcement for every router/frontend/statusd
    this suite constructs (the test_servd/test_routerd pattern)."""
    monkeypatch.setenv("CXXNET_LOCKRANK", "1")


def _drain_all(*objs):
    for o in objs:
        if o is None:
            continue
        if hasattr(o, "drain"):
            o.drain(timeout_ms=1000)
        elif hasattr(o, "stop"):
            o.stop()


def wait_until(cond, timeout=5.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.01)
    raise AssertionError("timed out waiting for " + msg)


def occupy_and_fill(fe, port):
    """Park one request inside a BLOCKING backend and one in the 1-slot
    queue, deterministically: waiting on ``accepted == 2`` alone is
    ambiguous — on a fast machine the second send can race the worker's
    pop of the first and be SHED instead of queued, leaving the queue
    empty and the next request queued behind the blocked backend
    instead of instantly shed. Returns the open sockets."""
    socks = []
    s = socket.create_connection(("127.0.0.1", port), timeout=5)
    s.sendall(b"9\n")
    socks.append(s)
    wait_until(lambda: fe._inflight == 1, msg="worker occupied")
    s = socket.create_connection(("127.0.0.1", port), timeout=5)
    s.sendall(b"9\n")
    socks.append(s)
    wait_until(lambda: len(fe._q) == 1, msg="queue full")
    return socks


# ----------------------------------------------------------------------
# servd: the TRACE prefix contract
def test_trace_prefix_adopted_and_validated():
    fe = servd.ServeFrontend(lambda toks, seq: [t + 1 for t in toks],
                             drain_ms=2000.0)
    fe.start()
    port = fe.listen(0)
    try:
        # TRACE-less requests keep their dense local ids (unchanged)
        assert faultinject.serve_request(port, "1 2") == "2 3"
        assert fe.flight.get("1")["outcome"] == "served"
        # a TRACE id is adopted as THE request id
        assert faultinject.serve_request(port, "TRACE fleet-7 5") == "6"
        rec = fe.flight.get("fleet-7")
        assert rec is not None and rec["outcome"] == "served"
        # composes with DEADLINE (TRACE first)
        assert faultinject.serve_request(
            port, "TRACE fleet-8 DEADLINE 5000 7") == "8"
        assert fe.flight.get("fleet-8") is not None
        # malformed ids: ERR proto with the machine-readable third token
        for bad in ("TRACE", "TRACE bad/id 1", "TRACE %s 1" % ("y" * 65),
                    "TRACE id,comma 1"):
            resp = faultinject.serve_request(port, bad)
            assert resp.startswith("ERR proto trace"), (bad, resp)
            assert not routerd.retryable(resp)
        # TRACE with no request line is the empty class, like a blank
        assert faultinject.serve_request(
            port, "TRACE fleet-9").startswith("ERR empty")
        # TRACE + ADMIN composes too (the prefix is stripped first)
        assert faultinject.serve_request(
            port, "TRACE fleet-a ADMIN stats").startswith("OK accepted=")
    finally:
        stats = fe.drain()
    assert stats["accepted"] == (stats["served"] + stats["errors"]
                                 + stats["shed"] + stats["deadline"])


def test_admission_shed_leaves_flight_record_under_trace_id():
    """A queue-full shed never dequeues, but it still files a flight
    record under the propagated id — that is what makes the shed hop
    visible in the stitched cross-process trace."""
    release = threading.Event()

    def slow(toks, seq):
        release.wait(10.0)
        return list(toks)

    telemetry.enable()               # in-memory: the shed's event
    fe = servd.ServeFrontend(slow, queue_size=1, drain_ms=2000.0)
    fe.start()
    port = fe.listen(0)
    socks = []
    try:
        socks += occupy_and_fill(fe, port)
        resp = faultinject.serve_request(port, "TRACE shed-1 5")
        assert resp.startswith("ERR busy queue"), resp
        rec = fe.flight.get("shed-1")
        assert rec is not None and rec["outcome"] == "shed", rec
        assert rec["shed_at"] == "queue"
        assert all(v == 0.0 for v in rec["phases"].values())
        assert rec["ttft_s"] is None
        # the shed emits a serve_request_done too — the OFFLINE --fleet
        # join needs the shed hop, not just the live stitch — with
        # NULL phases (never-dispatched events must not deflate the
        # report's percentile table)
        done = [e for e in telemetry.recent_events()
                if e.get("ev") == "serve_request_done"
                and e.get("req") == "shed-1"]
        assert len(done) == 1 and done[0]["outcome"] == "shed"
        assert done[0]["prefill_s"] is None \
            and done[0]["queue_wait_s"] is None
    finally:
        release.set()
        for s in socks:
            s.close()
        fe.drain()
        telemetry.disable()


# ----------------------------------------------------------------------
# router: minting, propagation, retry-under-one-id, stitched trace
def _fleet(n_backends):
    """n in-process replicas (frontend + statusd with the flight ring
    wired, global registry) behind a started router with probing and
    federation off the clock. Returns (router, [fe], [status])."""
    fes, sss = [], []
    for backend, kw in n_backends:
        fe = servd.ServeFrontend(backend, drain_ms=2000.0, **kw)
        fe.start()
        fe.listen(0)
        ss = statusd.StatusServer(0, host="127.0.0.1").start()
        ss.register_probe("serving", fe.health_probe)
        ss.flight = fe.flight
        fes.append(fe)
        sss.append(ss)
    router = routerd.Router(
        [("127.0.0.1", fe.port, ss.port) for fe, ss in zip(fes, sss)],
        probe_ms=3600e3, retries=2, stall_s=5.0, drain_ms=2000.0,
        federate_ms=3600e3, outlier_min_n=1)
    router.start()
    router.listen(0)
    return router, fes, sss


def test_retry_under_one_id_and_stitched_trace():
    """THE acceptance: a request retried across two replicas produces
    ONE stitched Chrome trace from router /trace?request=<id> whose
    router-lane spans and BOTH replicas' phase lanes share the id,
    with clock-aligned timestamps."""
    release = threading.Event()

    def wedged(toks, seq):
        release.wait(10.0)
        return list(toks)

    def fast(toks, seq):
        return [t + 1000 for t in toks]

    router, (fe1, fe2), (s1, s2) = _fleet(
        [(wedged, {"queue_size": 1}), (fast, {})])
    srv = statusd.StatusServer(0, host="127.0.0.1").start()
    srv.fleet = router
    srv.flight = router.flight
    socks = []
    try:
        # wedge replica 1 and fill its 1-slot queue so any pick of it
        # sheds ERR busy queue (zero load, index tie-break -> 1 first)
        socks += occupy_and_fill(fe1, fe1.port)
        assert faultinject.serve_request(router.port, "5") == "1005"
        rrec = router.flight.list()[0]
        tid = rrec["id"]
        assert rrec["outcome"] == "served" and rrec["retries"] == 1
        assert [a["replica"] for a in rrec["attempts"]] \
            == [router._replicas[0].name, router._replicas[1].name]
        assert rrec["attempts"][0]["outcome"].startswith("ERR busy")
        assert rrec["attempts"][0]["retried"] is True
        assert rrec["attempts"][1]["outcome"] == "served"
        # the pick-time candidates rode along (explainable routing)
        assert rrec["attempts"][0]["candidates"], rrec["attempts"][0]
        # ONE id on every process that touched the request — the shed
        # replica included
        assert fe1.flight.get(tid)["outcome"] == "shed"
        assert fe2.flight.get(tid)["outcome"] == "served"
        # the stitched trace off the router's statusd: router lanes
        # (pid 0) + BOTH replica lanes (pid 1, 2), every span tagged
        # with the id, timestamps clock-aligned on the wall epoch
        body = urlopen("http://127.0.0.1:%d/trace?request=%s"
                       % (srv.port, tid), timeout=5).read()
        trace = json.loads(body)
        xs = [t for t in trace["traceEvents"] if t.get("ph") == "X"]
        assert {t["pid"] for t in xs} == {0, 1, 2}, xs
        assert all(t["args"]["request"] == tid for t in xs)
        forwards = [t for t in xs if t["name"].startswith("forward:")]
        assert len(forwards) == 2
        # clock alignment: every lane's events land inside the router's
        # request window (same machine, shared wall clock; generous
        # slack for the wall-vs-monotonic stamp skew)
        req_span = next(t for t in xs if t["name"].startswith("route:"))
        t_hi = req_span["ts"] + req_span["dur"]
        for t in xs:
            assert -5e4 <= t["ts"] <= t_hi + 5e4, (t, t_hi)
        # the replica lanes carry the phase split (prefill present)
        assert any(t["name"] == "prefill" and t["pid"] == 2
                   for t in xs)
    finally:
        release.set()
        for s in socks:
            s.close()
        _drain_all(router, srv, s1, s2, fe1, fe2)


def test_pre_trace_replica_downgrade_and_latch():
    """Backward compat: a pre-PR-10 replica rejects the TRACE prefix
    itself as ERR parse; the router resends the bare line once (the
    parse rejection proves nothing dispatched), latches the replica
    no_trace, and serves the request — the client sees nothing."""
    lines = []

    class OldServer:
        """A pre-TRACE servd: integer tokens only, echo + 1."""

        def __init__(self):
            self.sock = socket.create_server(("127.0.0.1", 0))
            self.sock.settimeout(0.25)
            self.port = self.sock.getsockname()[1]
            self.alive = True
            threading.Thread(target=self._run, daemon=True).start()

        def _run(self):
            while self.alive:
                try:
                    conn, _ = self.sock.accept()
                except socket.timeout:
                    continue
                except OSError:
                    return
                with conn:
                    try:
                        line = conn.makefile("r").readline().strip()
                        lines.append(line)
                        try:
                            toks = [int(t) for t in line.split()]
                            resp = " ".join(str(t + 1) for t in toks)
                        except ValueError:
                            resp = "ERR parse non-integer token in request"
                        conn.sendall((resp + "\n").encode())
                    except OSError:
                        pass

        def stop(self):
            self.alive = False
            self.sock.close()

    old = OldServer()
    router = routerd.Router([("127.0.0.1", old.port, old.port)],
                            probe_ms=3600e3, retries=0, stall_s=5.0,
                            drain_ms=1000.0)
    router.start()
    router.listen(0)
    try:
        # first request: traced attempt rejected, bare resend served
        assert faultinject.serve_request(router.port, "1 2") == "2 3"
        assert len(lines) == 2 and lines[0].startswith("TRACE ")
        assert lines[1] == "1 2"
        assert router._replicas[0].no_trace is True
        rec = router.flight.list()[0]
        assert rec["outcome"] == "served"
        assert rec["attempts"][0].get("trace_downgraded") is True
        # latched: the next request goes bare on the FIRST try
        assert faultinject.serve_request(router.port, "7") == "8"
        assert len(lines) == 3 and lines[2] == "7"
        # a genuine client parse error is still relayed verbatim
        assert faultinject.serve_request(
            router.port, "x y").startswith("ERR parse")
    finally:
        _drain_all(router, old)


def test_genuine_parse_error_does_not_latch_new_replica():
    """A TRACE-capable replica answering ERR parse for a genuinely
    malformed request: the bare resend answers the same, the relay is
    verbatim, and the replica is NOT latched no_trace."""
    router, (fe,), (ss,) = _fleet(
        [(lambda toks, seq: list(toks), {})])
    try:
        assert faultinject.serve_request(
            router.port, "not numbers").startswith("ERR parse")
        assert router._replicas[0].no_trace is False
        # and a traced request still propagates normally afterwards
        assert faultinject.serve_request(router.port, "3") == "3"
        tid = router.flight.list()[0]["id"]
        assert fe.flight.get(tid) is not None
    finally:
        _drain_all(router, ss, fe)


def test_trace_ok_latch_skips_downgrade_resend():
    """Once a traced exchange succeeded, the replica has PROVEN it
    parses TRACE — later genuine client parse errors must not pay the
    downgrade resend (a malformed-request flood would otherwise hit
    the replica twice per request, forever)."""
    router, (fe,), (ss,) = _fleet(
        [(lambda toks, seq: [t + 1 for t in toks], {})])
    try:
        assert faultinject.serve_request(router.port, "1") == "2"
        assert router._replicas[0].trace_ok is True
        before = fe.stats()["accepted"]
        assert faultinject.serve_request(
            router.port, "not numbers").startswith("ERR parse")
        # exactly ONE replica-side request for the malformed line —
        # no bare resend against a proven-TRACE replica
        assert fe.stats()["accepted"] == before + 1
        assert router._replicas[0].no_trace is False
    finally:
        _drain_all(router, ss, fe)


def test_router_proto_err_and_client_id_adoption():
    router, (fe,), (ss,) = _fleet(
        [(lambda toks, seq: list(toks), {})])
    try:
        # the router validates TRACE like a replica would
        resp = faultinject.serve_request(router.port, "TRACE bad/id 1")
        assert resp.startswith("ERR proto trace"), resp
        # a client-minted id is adopted fleet-wide, not re-minted
        assert faultinject.serve_request(
            router.port, "TRACE mine-1 4") == "4"
        assert router.flight.get("mine-1")["outcome"] == "served"
        assert fe.flight.get("mine-1")["outcome"] == "served"
        st = router.stats()
        assert st["accepted"] == (st["served"] + st["errors"]
                                  + st["shed"] + st["deadline"])
    finally:
        _drain_all(router, ss, fe)


# ----------------------------------------------------------------------
# federation: exact histogram merge, fleet SLO, outlier detection
def _metric_statusd(hists, slo=None, counters=None):
    """A statusd over a PRIVATE registry pre-loaded with histograms —
    a stand-in replica for the federation pulls (no frontend needed:
    federation reads /metrics?json=1, nothing else)."""
    reg = telemetry._Registry()
    reg.enable()
    for name, values in hists.items():
        for v in values:
            reg.hist(name, v)
    for name, v in (counters or {}).items():
        reg.count(name, v)
    srv = statusd.StatusServer(0, host="127.0.0.1", registry=reg)
    srv.slo = slo
    return srv.start(), reg


def test_fleet_federation_exact_histogram_merge():
    """The acceptance: for every merged series, fleet bucket counts
    equal the SUM of the per-replica bucket counts (shared fixed
    buckets make the merge exact — no re-binning)."""
    s1, reg1 = _metric_statusd(
        {"serve.request": [0.001, 0.002, 0.004, 1.7],
         "serve.ttft": [0.0005, 0.003]},
        counters={"serve.accepted": 4, "serve.requests": 4})
    s2, reg2 = _metric_statusd(
        {"serve.request": [0.001, 0.09, 0.4],
         "serve.ttft": [0.01],
         "serve.queue_wait": [0.0001]},
        counters={"serve.accepted": 3, "serve.requests": 2})
    router = routerd.Router(
        [("127.0.0.1", 1, s1.port), ("127.0.0.1", 2, s2.port)],
        probe_ms=3600e3, federate_ms=3600e3, outlier_min_n=1)
    router.start()
    rsrv = statusd.StatusServer(0, host="127.0.0.1").start()
    rsrv.fleet = router
    try:
        assert router.federate_now() == 2
        fed = router.federation_snapshot()
        assert fed["replicas"] == 2
        shards = [reg1.metrics_snapshot()["hists"],
                  reg2.metrics_snapshot()["hists"]]
        assert set(fed["series"]) \
            == {"serve.request", "serve.ttft", "serve.queue_wait"}
        for name, h in fed["series"].items():
            expect = {}
            for shard in shards:
                for i, c in (shard.get(name, {}).get("buckets")
                             or {}).items():
                    expect[i] = expect.get(i, 0) + c
            assert h["buckets"] == expect, (name, h["buckets"], expect)
            assert h["count"] == sum(expect.values())
        # counters sum too
        assert fed["counters"]["serve.accepted"] == 7
        assert fed["counters"]["serve.requests"] == 6
        # and the router's own /metrics carries the federated series,
        # Prometheus-valid, with the summed +Inf bucket count
        metrics = urlopen("http://127.0.0.1:%d/metrics" % rsrv.port,
                          timeout=5).read().decode()
        for line in metrics.splitlines():
            if line and not line.startswith("#"):
                assert statusd.PROM_LINE_RE.match(line), line
        inf = [line for line in metrics.splitlines()
               if line.startswith("cxxnet_fleet_serve_request_seconds"
                                  "_bucket") and 'le="+Inf"' in line]
        assert inf and inf[0].rsplit(" ", 1)[1] == "7", inf
        assert "cxxnet_fleet_serve_accepted_total" in metrics
        assert "cxxnet_fleet_federated_replicas" in metrics
    finally:
        _drain_all(router, rsrv, s1, s2)


def test_fleet_slo_burn_fires_when_no_single_replica_does():
    """The acceptance: each replica stays under its own alert floor
    (bad < min_bad), so neither replica's cxxnet_slo_burn fires — but
    the fleet-wide merged window is over budget AND over the floors,
    so cxxnet_fleet_slo_burn does."""
    trackers = []
    servers = []
    for _ in range(2):
        slo = statusd.SLOTracker(availability=0.999, min_requests=10,
                                 min_bad=3, window_s=300.0)
        for _ in range(8):
            slo.observe(ok=True)
        for _ in range(2):           # 2 bad < min_bad=3: no page
            slo.observe(ok=False)
        assert slo.snapshot()["alert"] == 0, slo.snapshot()
        srv, _ = _metric_statusd({}, slo=slo)
        trackers.append(slo)
        servers.append(srv)
    router = routerd.Router(
        [("127.0.0.1", i + 1, s.port)
         for i, s in enumerate(servers)],
        probe_ms=3600e3, federate_ms=3600e3)
    router.start()
    rsrv = statusd.StatusServer(0, host="127.0.0.1").start()
    rsrv.fleet = router
    try:
        assert router.federate_now() == 2
        fslo = router.federation_snapshot()["slo"]
        assert fslo["requests"] == 20 and fslo["bad"] == 4
        assert fslo["burn_rate"] >= 1.0 and fslo["alert"] == 1, fslo
        metrics = urlopen("http://127.0.0.1:%d/metrics" % rsrv.port,
                          timeout=5).read().decode()
        assert "cxxnet_fleet_slo_burn" in metrics
        assert any(line.startswith("cxxnet_fleet_slo_burn{")
                   and line.endswith(" 1")
                   for line in metrics.splitlines()), metrics
    finally:
        _drain_all(router, rsrv, *servers)


def test_outlier_replica_detected_and_flagged():
    """One slow replica among three: its p99 diverges past the ratio
    from the fleet median -> outlier gauge 1, transition-only
    fleet_outlier event, flagged row on /fleetz."""
    telemetry.enable()               # in-memory: the transition events
    fast = [0.01] * 30
    servers = [
        _metric_statusd({"serve.request": fast})[0],
        _metric_statusd({"serve.request": fast})[0],
        _metric_statusd({"serve.request": [0.5] * 30})[0],
    ]
    router = routerd.Router(
        [("127.0.0.1", i + 1, s.port) for i, s in enumerate(servers)],
        probe_ms=3600e3, federate_ms=3600e3, outlier_ratio=3.0,
        outlier_min_n=10)
    router.start()
    rsrv = statusd.StatusServer(0, host="127.0.0.1").start()
    rsrv.fleet = router
    try:
        assert router.federate_now() == 3
        fed = router.federation_snapshot()
        slow_name = router._replicas[2].name
        assert fed["outliers"][slow_name]["outlier"] is True
        assert all(not fed["outliers"][r.name]["outlier"]
                   for r in router._replicas[:2])
        # transition-only event: a second identical sweep adds nothing
        evs = [e for e in telemetry.recent_events()
               if e.get("ev") == "fleet_outlier"]
        assert len(evs) == 1 and evs[0]["replica"] == slow_name
        assert evs[0]["outlier"] == 1
        assert router.federate_now() == 3
        evs = [e for e in telemetry.recent_events()
               if e.get("ev") == "fleet_outlier"]
        assert len(evs) == 1, evs
        # /fleetz flags the row; /metrics carries the per-replica gauge
        page = urlopen("http://127.0.0.1:%d/fleetz" % rsrv.port,
                       timeout=5).read().decode()
        assert "OUTLIER" in page
        fj = json.loads(urlopen("http://127.0.0.1:%d/fleetz?json=1"
                                % rsrv.port, timeout=5).read())
        slow_row = next(r for r in fj["replicas"]
                        if r["name"] == slow_name)
        assert slow_row["outlier"] is True
        metrics = urlopen("http://127.0.0.1:%d/metrics" % rsrv.port,
                          timeout=5).read().decode()
        assert ('cxxnet_fleet_outlier{process="0",replica="%s"} 1'
                % slow_name) in metrics
        assert "cxxnet_fleet_replica_p99_seconds" in metrics
        # a flagged replica that leaves the verdict set (dies) emits
        # its CLEARING transition — outlier=1 with no outlier=0 would
        # page forever on event-based alerting
        router._mark(router._replicas[2], routerd.DEAD, "killed")
        router.federate_now()
        evs = [e for e in telemetry.recent_events()
               if e.get("ev") == "fleet_outlier"]
        assert len(evs) == 2, evs
        assert evs[-1]["replica"] == slow_name \
            and evs[-1]["outlier"] == 0
    finally:
        _drain_all(router, rsrv, *servers)
        telemetry.disable()


def test_outlier_detected_in_two_replica_fleet():
    """Leave-one-out median: the common 2-replica topology can flag
    its slow half (an include-itself median of two values is their
    mean, which no ratio >= 2 can ever exceed)."""
    fast = _metric_statusd({"serve.request": [0.01] * 20})[0]
    slow = _metric_statusd({"serve.request": [1.0] * 20})[0]
    router = routerd.Router(
        [("127.0.0.1", 1, fast.port), ("127.0.0.1", 2, slow.port)],
        probe_ms=3600e3, federate_ms=3600e3, outlier_ratio=3.0,
        outlier_min_n=10)
    router.start()
    try:
        assert router.federate_now() == 2
        verdicts = router.federation_snapshot()["outliers"]
        assert verdicts[router._replicas[1].name]["outlier"] is True
        assert verdicts[router._replicas[0].name]["outlier"] is False
    finally:
        _drain_all(router, fast, slow)


def test_federation_keeps_last_known_snapshot_on_missed_sweep():
    """One transient scrape miss must not make the cxxnet_fleet_*
    counters/buckets dip (Prometheus would read a counter dip as a
    process reset and re-count the replica's lifetime totals): a live
    replica that missed a sweep keeps its last-known snapshot; only a
    DEAD replica leaves the merge."""
    s1 = _metric_statusd({"serve.request": [0.01] * 4},
                         counters={"serve.accepted": 4})[0]
    s2 = _metric_statusd({"serve.request": [0.02] * 3},
                         counters={"serve.accepted": 3})[0]
    router = routerd.Router(
        [("127.0.0.1", 1, s1.port), ("127.0.0.1", 2, s2.port)],
        probe_ms=3600e3, federate_ms=3600e3, outlier_min_n=1)
    router.start()
    try:
        assert router.federate_now() == 2
        assert router.federation_snapshot()["counters"][
            "serve.accepted"] == 7
        # replica 2's statusd goes away (scrape miss) but the replica
        # is NOT dead: its last-known contribution stays in the merge
        s2.stop()
        assert router.federate_now() == 1
        fed = router.federation_snapshot()
        assert fed["replicas"] == 2
        assert fed["counters"]["serve.accepted"] == 7, fed["counters"]
        assert fed["series"]["serve.request"]["count"] == 7
        # a DEAD replica's contribution does leave (a real reset)
        router._mark(router._replicas[1], routerd.DEAD, "killed")
        router.federate_now()
        fed = router.federation_snapshot()
        assert fed["replicas"] == 1
        assert fed["counters"]["serve.accepted"] == 4, fed["counters"]
    finally:
        _drain_all(router, s1)


# ----------------------------------------------------------------------
# multi-tenant QoS on the observability plane (ISSUE 13)
def test_pre_tenant_replica_downgrade_ladder():
    """Backward compat, the TENANT edition of the TRACE downgrade: a
    pre-TENANT replica rejects the prefixed line as ERR parse; the
    router walks the ladder (drop TENANT, then TRACE too), serves the
    request bare, and latches what the replica cannot speak — the
    client sees nothing. A pre-TRACE replica latches BOTH."""
    lines = []

    class OldServer:
        """A pre-TRACE, pre-TENANT servd: integer tokens only."""

        def __init__(self):
            self.sock = socket.create_server(("127.0.0.1", 0))
            self.sock.settimeout(0.25)
            self.port = self.sock.getsockname()[1]
            self.alive = True
            threading.Thread(target=self._run, daemon=True).start()

        def _run(self):
            while self.alive:
                try:
                    conn, _ = self.sock.accept()
                except socket.timeout:
                    continue
                except OSError:
                    return
                with conn:
                    try:
                        line = conn.makefile("r").readline().strip()
                        lines.append(line)
                        try:
                            toks = [int(t) for t in line.split()]
                            resp = " ".join(str(t + 1) for t in toks)
                        except ValueError:
                            resp = ("ERR parse non-integer token in "
                                    "request")
                        conn.sendall((resp + "\n").encode())
                    except OSError:
                        pass

        def stop(self):
            self.alive = False
            self.sock.close()

    old = OldServer()
    router = routerd.Router([("127.0.0.1", old.port, old.port)],
                            probe_ms=3600e3, retries=0, stall_s=5.0,
                            drain_ms=1000.0,
                            tenants="noisy:1,victim:4",
                            tenant_default="victim")
    router.start()
    router.listen(0)
    try:
        # first request: TRACE+TENANT rejected, TRACE-only rejected,
        # bare served — the full ladder, one wire line per rung
        assert faultinject.serve_request(
            router.port, "TENANT noisy 1 2") == "2 3"
        assert len(lines) == 3, lines
        assert lines[0].split()[0] == "TRACE" \
            and lines[0].split()[2] == "TENANT"
        assert lines[1].split()[0] == "TRACE" \
            and "TENANT" not in lines[1]
        assert lines[2] == "1 2"
        r = router._replicas[0]
        assert r.no_trace is True and r.no_tenant is True
        # latched: the next request goes bare on the FIRST wire line,
        # and the tenant is still ACCOUNTED router-side
        assert faultinject.serve_request(
            router.port, "TENANT noisy 7") == "8"
        assert len(lines) == 4 and lines[3] == "7"
        ts = router.tenant_stats()
        assert ts["noisy"]["accepted"] == 2 \
            and ts["noisy"]["served"] == 2
    finally:
        _drain_all(router, old)


def test_tenant_downgrade_skipped_for_proven_replica():
    """The positive latch, TENANT edition: one successful tenant-
    prefixed exchange proves the replica parses TENANT — a later
    genuine client parse error pays NO downgrade resends."""
    fe = servd.ServeFrontend(lambda toks, seq: [t + 1 for t in toks],
                             drain_ms=2000.0,
                             tenants="noisy:1,victim:4",
                             tenant_default="victim")
    fe.start()
    fe.listen(0)
    ss = statusd.StatusServer(0, host="127.0.0.1").start()
    ss.register_probe("serving", fe.health_probe)
    router = routerd.Router([("127.0.0.1", fe.port, ss.port)],
                            probe_ms=3600e3, retries=0, stall_s=5.0,
                            drain_ms=1000.0,
                            tenants="noisy:1,victim:4",
                            tenant_default="victim")
    router.start()
    router.listen(0)
    try:
        assert faultinject.serve_request(
            router.port, "TENANT noisy 1") == "2"
        r = router._replicas[0]
        assert r.trace_ok is True and r.tenant_ok is True
        before = fe.stats()["accepted"]
        assert faultinject.serve_request(
            router.port, "TENANT noisy not numbers") \
            .startswith("ERR parse")
        # exactly ONE replica-side request for the malformed line
        assert fe.stats()["accepted"] == before + 1
        assert r.no_trace is False and r.no_tenant is False
    finally:
        _drain_all(router, ss, fe)


def test_per_tenant_federation_series_and_slo():
    """The per-tenant fleet account: serve.tenant.* counters sum
    exactly, per-tenant hists merge into a fleet p99, per-tenant SLO
    windows merge (victim holds, noisy burns), and the router's
    statusd renders the cxxnet_fleet_tenant_*{tenant=} label rows and
    the cxxnet_slo_tenant_* replica rows — all Prometheus-valid."""
    noisy_slo = statusd.SLOTracker(availability=0.99, min_requests=4,
                                   min_bad=3, window_s=300.0)
    victim_slo = statusd.SLOTracker(availability=0.99, min_requests=4,
                                    min_bad=3, window_s=300.0)
    for _ in range(6):
        noisy_slo.observe(ok=False)
        victim_slo.observe(ok=True)
    shards = []
    for k in (2, 3):
        srv, reg = _metric_statusd(
            {"serve.tenant.noisy.request": [0.001] * k,
             "serve.tenant.victim.request": [0.01] * k},
            counters={"serve.tenant.noisy.accepted": 5 * k,
                      "serve.tenant.noisy.shed": 4 * k,
                      "serve.tenant.victim.accepted": 2 * k,
                      "serve.tenant.victim.served": 2 * k})
        srv.slo_tenants = {"noisy": noisy_slo, "victim": victim_slo}
        shards.append(srv)
    router = routerd.Router(
        [("127.0.0.1", i + 1, s.port)
         for i, s in enumerate(shards)],
        probe_ms=3600e3, federate_ms=3600e3, outlier_min_n=1,
        tenants="noisy:1,victim:4", tenant_default="victim")
    router.start()
    rsrv = statusd.StatusServer(0, host="127.0.0.1").start()
    rsrv.fleet = router
    try:
        assert router.federate_now() == 2
        fed = router.federation_snapshot()
        # counters summed per tenant; fleet p99 from the merged hist
        assert fed["tenants"]["noisy"]["accepted"] == 25
        assert fed["tenants"]["noisy"]["shed"] == 20
        assert fed["tenants"]["victim"]["served"] == 10
        assert fed["tenants"]["noisy"]["p99_ms"] is not None
        # per-tenant merged windows: noisy burns (both shards observed
        # the same trackers here — the merge path is what's pinned),
        # victim holds 0
        assert fed["slo_tenants"]["noisy"]["alert"] == 1
        assert fed["slo_tenants"]["victim"]["alert"] == 0
        # label rows on the router's /metrics, Prometheus-valid
        metrics = urlopen("http://127.0.0.1:%d/metrics" % rsrv.port,
                          timeout=5).read().decode()
        for line in metrics.splitlines():
            if line and not line.startswith("#"):
                assert statusd.PROM_LINE_RE.match(line), line
        assert ('cxxnet_fleet_tenant_weight{process="0",'
                'tenant="victim"} 4') in metrics
        assert 'cxxnet_fleet_tenant_slo_burn{' in metrics
        assert 'cxxnet_fleet_tenant_p99_seconds{' in metrics
        # ... and the /fleetz tenants section renders
        page = urlopen("http://127.0.0.1:%d/fleetz" % rsrv.port,
                       timeout=5).read().decode()
        assert "tenants (weighted-fair QoS)" in page
        # the replica-side per-tenant rows + json federation feed
        rep_metrics = urlopen("http://127.0.0.1:%d/metrics"
                              % shards[0].port,
                              timeout=5).read().decode()
        assert 'cxxnet_slo_tenant_burn{process="0",tenant="noisy"} 1' \
            in rep_metrics
        mj = json.loads(urlopen("http://127.0.0.1:%d/metrics?json=1"
                                % shards[0].port, timeout=5).read())
        assert mj["slo_tenants"]["victim"]["alert"] == 0
    finally:
        _drain_all(router, rsrv, *shards)


def test_bench_compare_tenant_subfield_directions(tmp_path):
    """Direction-aware gating for the serve_tenant_isolation row:
    victim_p99_ms and fleet_scale_admission_latency_s gate worse-when-HIGHER,
    noisy_shed_rate worse-when-LOWER (a drop means the flood got
    through)."""
    import subprocess
    import sys
    bench = tmp_path / "BENCH_r99.json"
    bench.write_text(json.dumps({
        "metric": "serve_tenant_isolation", "value": 50.0,
        "unit": "ms", "victim_p99_ms": 50.0, "noisy_shed_rate": 0.2,
        "fleet_scale_admission_latency_s": 2.0}) + "\n")
    base = tmp_path / "BASELINE.json"
    base.write_text(json.dumps({"published": {
        "serve_tenant_isolation": 50.0,
        "serve_tenant_isolation.victim_p99_ms": 25.0,
        "serve_tenant_isolation.noisy_shed_rate": 0.9,
        "serve_tenant_isolation.fleet_scale_admission_latency_s": 0.5}}))
    proc = subprocess.run(
        [sys.executable, "tools/bench_compare.py", "--bench",
         str(bench), "--baseline", str(base)],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 2, proc.stdout
    out = proc.stdout
    # all three regressed in their own direction
    assert out.count("REGRESSION") == 3, out
    assert "victim_p99_ms" in out and "noisy_shed_rate" in out \
        and "fleet_scale_admission_latency_s" in out
    # and the good direction passes: higher shed rate, lower latency
    bench.write_text(json.dumps({
        "metric": "serve_tenant_isolation", "value": 50.0,
        "unit": "ms", "victim_p99_ms": 20.0, "noisy_shed_rate": 0.95,
        "fleet_scale_admission_latency_s": 0.3}) + "\n")
    proc = subprocess.run(
        [sys.executable, "tools/bench_compare.py", "--bench",
         str(bench), "--baseline", str(base)],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stdout


def test_bench_compare_failover_subfield_directions(tmp_path):
    """Direction-aware gating for the failover rows:
    serve_chaos_availability (pct) and its replays sub-field gate
    worse-when-LOWER (a drop toward zero means the failover datapath
    stopped firing), error_rate / kill_window_p99_ms worse-when-HIGHER
    via the existing rate/latency rules; on serve_hedged_tail the
    headline hedged p99 is a latency while hedges/hedge_wins gate
    worse-when-LOWER."""
    import subprocess
    import sys
    bench = tmp_path / "BENCH_r99.json"
    bench.write_text("\n".join([
        json.dumps({"metric": "serve_chaos_availability", "value": 60.0,
                    "unit": "pct", "replays": 0, "error_rate": 0.4,
                    "kill_window_p99_ms": 900.0}),
        json.dumps({"metric": "serve_hedged_tail", "value": 400.0,
                    "unit": "ms", "hedges": 0, "hedge_wins": 0})]) + "\n")
    base = tmp_path / "BASELINE.json"
    base.write_text(json.dumps({"published": {
        "serve_chaos_availability": 99.0,
        "serve_chaos_availability.replays": 3.0,
        "serve_chaos_availability.error_rate": 0.01,
        "serve_chaos_availability.kill_window_p99_ms": 150.0,
        "serve_hedged_tail": 50.0,
        "serve_hedged_tail.hedges": 2.0,
        "serve_hedged_tail.hedge_wins": 2.0}}))
    proc = subprocess.run(
        [sys.executable, "tools/bench_compare.py", "--bench",
         str(bench), "--baseline", str(base)],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 2, proc.stdout
    out = proc.stdout
    # every field regressed in its own direction: availability and
    # the engagement counters fell, error rate and latencies rose
    assert out.count("REGRESSION") == 7, out
    assert "replays" in out and "hedges" in out and "hedge_wins" in out
    # and the good directions pass
    bench.write_text("\n".join([
        json.dumps({"metric": "serve_chaos_availability",
                    "value": 100.0, "unit": "pct", "replays": 5,
                    "error_rate": 0.0, "kill_window_p99_ms": 100.0}),
        json.dumps({"metric": "serve_hedged_tail", "value": 40.0,
                    "unit": "ms", "hedges": 4, "hedge_wins": 3})]) + "\n")
    proc = subprocess.run(
        [sys.executable, "tools/bench_compare.py", "--bench",
         str(bench), "--baseline", str(base)],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stdout


def test_bench_compare_decode_subfield_directions(tmp_path):
    """Direction-aware gating for the serve_throughput_rps decode
    sub-fields: kv_live_pct gates worse-when-LOWER (a drop = more
    padding/dead-slot waste — the paged-KV baseline regressing),
    queue_age_p99_ms worse-when-HIGHER via the *_ms rule."""
    import subprocess
    import sys
    bench = tmp_path / "BENCH_r99.json"
    bench.write_text(json.dumps({
        "metric": "serve_throughput_rps", "value": 8.0,
        "unit": "req/s", "kv_live_pct": 10.0,
        "queue_age_p99_ms": 900.0}) + "\n")
    base = tmp_path / "BASELINE.json"
    base.write_text(json.dumps({"published": {
        "serve_throughput_rps": 8.0,
        "serve_throughput_rps.kv_live_pct": 40.0,
        "serve_throughput_rps.queue_age_p99_ms": 100.0}}))
    proc = subprocess.run(
        [sys.executable, "tools/bench_compare.py", "--bench",
         str(bench), "--baseline", str(base)],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 2, proc.stdout
    out = proc.stdout
    assert out.count("REGRESSION") == 2, out
    assert "kv_live_pct" in out and "queue_age_p99_ms" in out
    # the good directions pass: higher utilization, lower queue age
    bench.write_text(json.dumps({
        "metric": "serve_throughput_rps", "value": 8.0,
        "unit": "req/s", "kv_live_pct": 60.0,
        "queue_age_p99_ms": 50.0}) + "\n")
    proc = subprocess.run(
        [sys.executable, "tools/bench_compare.py", "--bench",
         str(bench), "--baseline", str(base)],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stdout


def test_bench_compare_multiturn_subfield_directions(tmp_path):
    """Direction-aware gating for the serve_multiturn_ttft row (the
    retained conversation cache, doc/robustness.md "Memory
    governance"): kv_retained_pct, retained_hit_rate and ttft_speedup
    gate worse-when-LOWER (a drop means the retained cache stopped
    holding mass / paying), cold_ttft_ms and the ms-unit headline
    worse-when-HIGHER via the ttft/latency rules."""
    import subprocess
    import sys
    bench = tmp_path / "BENCH_r99.json"
    bench.write_text(json.dumps({
        "metric": "serve_multiturn_ttft", "value": 40.0,
        "unit": "ms", "cold_ttft_ms": 80.0, "ttft_speedup": 1.0,
        "kv_retained_pct": 10.0, "retained_hit_rate": 5.0}) + "\n")
    base = tmp_path / "BASELINE.json"
    base.write_text(json.dumps({"published": {
        "serve_multiturn_ttft": 25.0,
        "serve_multiturn_ttft.cold_ttft_ms": 45.0,
        "serve_multiturn_ttft.ttft_speedup": 1.8,
        "serve_multiturn_ttft.kv_retained_pct": 60.0,
        "serve_multiturn_ttft.retained_hit_rate": 45.0}}))
    proc = subprocess.run(
        [sys.executable, "tools/bench_compare.py", "--bench",
         str(bench), "--baseline", str(base)],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 2, proc.stdout
    out = proc.stdout
    # every field regressed in its own direction
    assert out.count("REGRESSION") == 5, out
    for field in ("cold_ttft_ms", "ttft_speedup", "kv_retained_pct",
                  "retained_hit_rate"):
        assert field in out, (field, out)
    # the good directions pass: faster warm TTFT, bigger speedup,
    # more retained mass — and a slower COLD pass is a regression of
    # the baseline path, still gated worse-when-higher, so keep it flat
    bench.write_text(json.dumps({
        "metric": "serve_multiturn_ttft", "value": 20.0,
        "unit": "ms", "cold_ttft_ms": 45.0, "ttft_speedup": 2.2,
        "kv_retained_pct": 70.0, "retained_hit_rate": 50.0}) + "\n")
    proc = subprocess.run(
        [sys.executable, "tools/bench_compare.py", "--bench",
         str(bench), "--baseline", str(base)],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stdout


# ----------------------------------------------------------------------
# the offline --fleet report join
def test_fleet_report_joins_router_and_replica_shards(tmp_path, capsys):
    import subprocess
    import sys

    router_log = tmp_path / "router.jsonl"
    rep_a = tmp_path / "rep_a.jsonl"
    rep_b = tmp_path / "rep_b.jsonl"
    router_log.write_text("\n".join(json.dumps(e) for e in [
        {"ev": "meta", "pid": 1, "t0_wall": 1000.0, "ts": 0.0, "p": 0},
        {"ev": "route_request_done", "req": "f-1", "outcome": "served",
         "attempts": 2, "retries": 1,
         "replicas": ["127.0.0.1:71", "127.0.0.1:72"],
         "total_s": 0.25, "ts": 1.0, "p": 0},
        {"ev": "fleet_outlier", "replica": "127.0.0.1:72",
         "outlier": 1, "p99_ms": 90.0, "fleet_p99_ms": 25.0,
         "ts": 2.0, "p": 0},
    ]) + "\n")
    rep_a.write_text("\n".join(json.dumps(e) for e in [
        {"ev": "meta", "pid": 2, "t0_wall": 1000.2, "ts": 0.0, "p": 0},
        {"ev": "serve_request_done", "req": "f-1", "outcome": "shed",
         "tokens": 0, "total_s": 0.0, "queue_wait_s": 0.0,
         "dispatch_s": 0.0, "prefill_s": None, "decode_s": None,
         "recompiles": 0, "ts": 0.8, "p": 0},
    ]) + "\n")
    rep_b.write_text("\n".join(json.dumps(e) for e in [
        {"ev": "meta", "pid": 3, "t0_wall": 1000.1, "ts": 0.0, "p": 0},
        {"ev": "serve_request_done", "req": "f-1", "outcome": "served",
         "tokens": 8, "total_s": 0.2, "ttft_s": 0.04,
         "queue_wait_s": 0.001, "dispatch_s": 0.0001,
         "prefill_s": 0.04, "decode_s": 0.155, "recompiles": 0,
         "ts": 1.1, "p": 0},
    ]) + "\n")
    proc = subprocess.run(
        [sys.executable, "tools/telemetry_report.py", "--fleet",
         str(router_log), str(rep_a), str(rep_b)],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stderr
    out = proc.stdout
    assert "fleet requests (router <-> replica join on trace id)" in out
    assert "routed: 1" in out and "retried: 1" in out
    assert "hop-matched: 1" in out
    # both hops rendered under the one router request, shed + served
    assert "hop p=1" in out and "hop p=2" in out
    assert "router overhead" in out
    assert "OUTLIER" in out
    # duplicate-p shards are exactly why --fleet relabels: --merge on
    # the same inputs refuses
    proc2 = subprocess.run(
        [sys.executable, "tools/telemetry_report.py", "--merge",
         str(router_log), str(rep_a)],
        capture_output=True, text=True, cwd=REPO)
    assert proc2.returncode != 0


# ----------------------------------------------------------------------
# statusd: /requestz parameters on a serving process
class _FakeBatch:
    """A batch_snapshot provider for a stand-in replica's statusd —
    the federation reads the /metrics?json=1 "batch" key, nothing
    else, so the fake needs only the snapshot dict."""

    def __init__(self, snap):
        self._snap = snap
        self.batch_flight = None

    def batch_snapshot(self, ring: int = 0):
        return dict(self._snap)


def test_fleet_decode_account_federates_exactly():
    """The decode KV/convoy account federates EXACTLY: byte sums over
    the replicas' own accounts, live pct recomputed from the sums
    (never a mean of means), convoy replicas counted — and the
    serve.queue_age histogram rides the existing exact serve-series
    merge into cxxnet_fleet_serve_queue_age_seconds."""
    s1, reg1 = _metric_statusd(
        {"serve.queue_age": [0.01, 0.2]},
        counters={"serve.convoys": 1})
    s1.batch = _FakeBatch({"kv_bytes": 1000, "kv_live_bytes": 900,
                           "kv_live_pct": 90.0, "convoy": 1,
                           "convoys": 1, "buckets": {}})
    s2, reg2 = _metric_statusd({"serve.queue_age": [0.05]})
    s2.batch = _FakeBatch({"kv_bytes": 3000, "kv_live_bytes": 300,
                           "kv_live_pct": 10.0, "convoy": 0,
                           "convoys": 0, "buckets": {}})
    router = routerd.Router(
        [("127.0.0.1", 1, s1.port), ("127.0.0.1", 2, s2.port)],
        probe_ms=3600e3, federate_ms=3600e3, outlier_min_n=1)
    router.start()
    rsrv = statusd.StatusServer(0, host="127.0.0.1").start()
    rsrv.fleet = router
    try:
        assert router.federate_now() == 2
        fed = router.federation_snapshot()
        dec = fed["decode"]
        assert dec["replicas"] == 2
        assert dec["kv_bytes"] == 4000
        assert dec["kv_live_bytes"] == 1200
        # 1200/4000 = 30% — the EXACT fleet ratio; a mean of the
        # per-replica pcts (90+10)/2 = 50% would be the lie
        assert dec["kv_live_pct"] == 30.0
        assert dec["convoy_replicas"] == 1
        metrics = urlopen("http://127.0.0.1:%d/metrics" % rsrv.port,
                          timeout=5).read().decode()
        for line in metrics.splitlines():
            if line and not line.startswith("#"):
                assert statusd.PROM_LINE_RE.match(line), line
        assert "cxxnet_fleet_decode_kv_bytes" in metrics
        assert "cxxnet_fleet_decode_kv_live_pct" in metrics
        assert "cxxnet_fleet_decode_convoy_replicas" in metrics
        # the queue-age histogram merged exactly (3 observations)
        inf = [line for line in metrics.splitlines()
               if line.startswith("cxxnet_fleet_serve_queue_age_"
                                  "seconds_bucket")
               and 'le="+Inf"' in line]
        assert inf and inf[0].rsplit(" ", 1)[1] == "3", inf
        # the episode counter sums through the serve.* counter merge
        assert "cxxnet_fleet_serve_convoys_total" in metrics
    finally:
        _drain_all(router, rsrv, s1, s2)


def test_fleetz_shows_per_bucket_batch_load():
    """The router parses ADMIN stats' batch_buckets / bucket.<b>.*
    keys off a REAL batching replica and surfaces them on /fleetz —
    the per-bucket load signal disaggregation will route on."""
    sb = faultinject.slot_backend(buckets=(2, 4), n_new=30,
                                  per_token_s=0.01)
    fe = servd.ServeFrontend(None, slot_backend=sb, batch_max=4,
                             batch_window_ms=0.0, drain_ms=8000.0)
    fe.start()
    port = fe.listen(0)
    ss = statusd.StatusServer(0, host="127.0.0.1").start()
    ss.register_probe("serving", fe.health_probe)
    router = routerd.Router([("127.0.0.1", port, ss.port)],
                            probe_ms=3600e3, federate_ms=3600e3)
    router.start()
    rsrv = statusd.StatusServer(0, host="127.0.0.1").start()
    rsrv.fleet = router
    ts = []
    try:
        ts = [threading.Thread(
            target=faultinject.serve_request,
            args=(port, "%d00" % (i + 1),), kwargs={"timeout": 30.0})
            for i in range(2)]
        for t in ts:
            t.start()
        wait_until(lambda: fe.batch_snapshot()["buckets"]["2"]
                   ["active"] == 2, msg="batch underway")
        router.probe_now()
        snap = router.fleet_snapshot()
        rep = snap["replicas"][0]
        assert rep["buckets"]["2"] == {"warm": 1, "active": 2}
        assert rep["buckets"]["4"] == {"warm": 0, "active": 0}
        page = urlopen("http://127.0.0.1:%d/fleetz" % rsrv.port,
                       timeout=5).read().decode()
        assert "2:2/2" in page, page
    finally:
        for t in ts:
            t.join()
        _drain_all(router, rsrv, fe, ss)


def test_fleet_federates_retained_pool_and_pressure():
    """The retained conversation cache federates EXACTLY
    (doc/robustness.md "Memory governance"): block/hit/token sums over
    the replicas' own pools, the fleet retained hit rate recomputed
    from the TOKEN sums (never a mean of per-replica rates), and
    pressure_replicas counting latched replicas — all riding the
    cxxnet_fleet_decode_* series and the /fleetz paged-kv line."""
    s1, _reg1 = _metric_statusd({})
    s1.batch = _FakeBatch({
        "kv_bytes": 0, "kv_live_bytes": 0, "convoy": 0, "convoys": 0,
        "buckets": {}, "pool": {
            "blocks_total": 8, "blocks_free": 1, "blocks_retained": 5,
            "prefix_hit_tokens": 30, "prompt_tokens": 100,
            "alloc_failures": 0, "retained_hits": 2,
            "retained_hit_tokens": 30, "pressure": 1}})
    s2, _reg2 = _metric_statusd({})
    s2.batch = _FakeBatch({
        "kv_bytes": 0, "kv_live_bytes": 0, "convoy": 0, "convoys": 0,
        "buckets": {}, "pool": {
            "blocks_total": 8, "blocks_free": 6, "blocks_retained": 1,
            "prefix_hit_tokens": 10, "prompt_tokens": 300,
            "alloc_failures": 0, "retained_hits": 1,
            "retained_hit_tokens": 10, "pressure": 0}})
    router = routerd.Router(
        [("127.0.0.1", 1, s1.port), ("127.0.0.1", 2, s2.port)],
        probe_ms=3600e3, federate_ms=3600e3, outlier_min_n=1)
    router.start()
    rsrv = statusd.StatusServer(0, host="127.0.0.1").start()
    rsrv.fleet = router
    try:
        assert router.federate_now() == 2
        pl = router.federation_snapshot()["decode"]["pool"]
        assert pl["blocks_retained"] == 6
        assert pl["retained_hits"] == 3
        assert pl["retained_hit_tokens"] == 40
        # 40/400 = 10% — the EXACT fleet ratio; a mean of the
        # per-replica rates (30% + 3.33%)/2 ≈ 16.7% would be the lie
        assert pl["retained_hit_rate"] == 10.0
        assert pl["pressure_replicas"] == 1
        metrics = urlopen("http://127.0.0.1:%d/metrics" % rsrv.port,
                          timeout=5).read().decode()
        for line in metrics.splitlines():
            if line and not line.startswith("#"):
                assert statusd.PROM_LINE_RE.match(line), line
        want = {"cxxnet_fleet_decode_kv_block_retained": "6",
                "cxxnet_fleet_decode_retained_hits_total": "3",
                "cxxnet_fleet_decode_retained_hit_rate": "10.0",
                "cxxnet_fleet_decode_kv_pressure_replicas": "1"}
        for name, val in want.items():
            row = [ln for ln in metrics.splitlines()
                   if ln.startswith(name + " ")
                   or ln.startswith(name + "{")]
            assert len(row) == 1 and row[0].endswith(" " + val), \
                (name, row)
        page = urlopen("http://127.0.0.1:%d/fleetz" % rsrv.port,
                       timeout=5).read().decode()
        assert "PRESSURE on 1 replica(s)" in page, page
        assert "6 retained" in page, page
    finally:
        _drain_all(router, rsrv, s1, s2)


def test_fleetz_retained_column_and_garbage_guard(monkeypatch):
    """The router parses ADMIN stats' kv_retained_blocks /
    kv_retained_hits off a REAL retaining replica onto the /fleetz
    retained column; a replica WITHOUT the retained cache renders "-"
    (absence is the capability signal, never a lying 0); and garbage
    values from a foreign replica zero the fields instead of killing
    the prober thread (the PR 13 guard discipline)."""
    sb = faultinject.slot_backend(buckets=(4,), n_new=4,
                                  kv_pool_blocks=8, kv_block_tokens=4,
                                  kv_retained_frac=1.0)
    fe = servd.ServeFrontend(None, slot_backend=sb, batch_max=4,
                             batch_window_ms=0.0, drain_ms=8000.0)
    fe.start()
    port = fe.listen(0)
    ss = statusd.StatusServer(0, host="127.0.0.1").start()
    ss.register_probe("serving", fe.health_probe)
    # the retention-less replica: plain echo, no slot backend
    fe2 = servd.ServeFrontend(lambda toks, seq: [t + 1 for t in toks],
                              drain_ms=2000.0)
    fe2.start()
    port2 = fe2.listen(0)
    ss2 = statusd.StatusServer(0, host="127.0.0.1").start()
    ss2.register_probe("serving", fe2.health_probe)
    router = routerd.Router([("127.0.0.1", port, ss.port),
                             ("127.0.0.1", port2, ss2.port)],
                            probe_ms=3600e3, federate_ms=3600e3)
    router.start()
    rsrv = statusd.StatusServer(0, host="127.0.0.1").start()
    rsrv.fleet = router
    try:
        # turn 1 retires into the retained pool; turn 2 extends the
        # same conversation and REVIVES it (>= 1 retained hit)
        faultinject.serve_request(
            port, " ".join(str(t) for t in range(1, 9)), timeout=30.0)
        faultinject.serve_request(
            port, " ".join(str(t) for t in range(1, 13)), timeout=30.0)
        router.probe_now()
        reps = {r["name"]: r
                for r in router.fleet_snapshot()["replicas"]}
        warm = reps["127.0.0.1:%d" % port]
        bare = reps["127.0.0.1:%d" % port2]
        assert warm["kv_retained_hits"] >= 1, warm
        assert isinstance(warm["kv_retained_blocks"], int)
        assert bare["kv_retained_blocks"] is None
        assert bare["kv_retained_hits"] is None
        page = urlopen("http://127.0.0.1:%d/fleetz" % rsrv.port,
                       timeout=5).read().decode()
        assert "%s:%s" % (warm["kv_retained_blocks"],
                          warm["kv_retained_hits"]) in page, page
        # a foreign replica answering garbage for the retained keys:
        # the guarded parse zeroes the fields, the prober survives
        monkeypatch.setattr(
            router, "_replica_stats",
            lambda r: {"queue_depth": 0, "in_flight": 0,
                       "kv_retained_blocks": "grue",
                       "kv_retained_hits": []})
        router.probe_now()        # must not raise / kill the prober
        reps = {r["name"]: r
                for r in router.fleet_snapshot()["replicas"]}
        warm = reps["127.0.0.1:%d" % port]
        assert warm["kv_retained_blocks"] == 0
        assert warm["kv_retained_hits"] == 0
    finally:
        _drain_all(router, rsrv, fe, ss, fe2, ss2)


def test_batchz_and_metrics_render_retained_cache():
    """statusd renders the retained-cache account: the /batchz
    "retained cache:" line (parked/cap/revivals/hit-pct/evictions +
    the MEMORY PRESSURE flag) and the per-process
    cxxnet_decode_retained_* / cxxnet_decode_kv_pressure series —
    pure render off the published pool snapshot."""
    srv = statusd.StatusServer(0, host="127.0.0.1").start()
    srv.batch = _FakeBatch({
        "kv_bytes": 1 << 20, "kv_live_bytes": 1 << 19, "convoy": 0,
        "convoys": 0, "buckets": {}, "pool": {
            "blocks_total": 16, "blocks_free": 4, "block_tokens": 8,
            "pool_bytes": 1 << 20, "prefix_hits": 3,
            "prefix_queries": 5, "prefix_hit_rate": 40.0,
            "prefix_hit_tokens": 40, "prompt_tokens": 100,
            "cow_copies": 0, "alloc_failures": 0,
            "blocks_retained": 5, "retained_cap": 15,
            "retained_hits": 2, "retained_hit_tokens": 30,
            "retained_hit_rate": 30.0, "retained_evictions": 4,
            "kv_retained_pct": 31.25, "pressure": 1}})
    base = "http://127.0.0.1:%d" % srv.port
    try:
        page = urlopen(base + "/batchz", timeout=5).read().decode()
        assert "retained cache: 5 block(s) parked (cap 15), " \
            "2 revival(s) (30.0% of prompt tokens), 4 eviction(s)" \
            in page, page
        assert "MEMORY PRESSURE (shedding)" in page, page
        metrics = urlopen(base + "/metrics", timeout=5).read().decode()
        for line in metrics.splitlines():
            if line and not line.startswith("#"):
                assert statusd.PROM_LINE_RE.match(line), line
        want = {"cxxnet_decode_kv_block_retained": "5",
                "cxxnet_decode_retained_hits_total": "2",
                "cxxnet_decode_retained_hit_tokens_total": "30",
                "cxxnet_decode_retained_evictions_total": "4",
                "cxxnet_decode_retained_hit_rate": "30.0",
                "cxxnet_decode_kv_pressure": "1"}
        for name, val in want.items():
            row = [ln for ln in metrics.splitlines()
                   if ln.startswith(name)]
            assert len(row) == 1 and row[0].endswith(" " + val), \
                (name, row)
    finally:
        _drain_all(srv)


def test_requestz_limit_json_and_single_record():
    fr = telemetry.FlightRecorder(cap=8)
    for i in range(6):
        fr.record({"id": "q-%d" % i, "outcome": "served",
                   "total_s": 0.01 * i, "ttft_s": 0.001,
                   "tokens_out": i,
                   "phases": {"queue_wait": 0.0, "dispatch": 0.0,
                              "prefill": 0.01 * i, "decode": 0.0},
                   "recompiles": []})
    srv = statusd.StatusServer(0, host="127.0.0.1").start()
    srv.flight = fr
    base = "http://127.0.0.1:%d" % srv.port
    try:
        page = urlopen(base + "/requestz", timeout=5).read().decode()
        assert "q-5" in page and "flight recorder" in page
        j = json.loads(urlopen(base + "/requestz?json=1&n=2",
                               timeout=5).read())
        assert j["shown"] == 2 and j["total"] == 6
        assert [r["id"] for r in j["requests"]] == ["q-5", "q-4"]
        one = json.loads(urlopen(base + "/requestz?request=q-3",
                                 timeout=5).read())
        assert one["id"] == "q-3"
        from urllib.error import HTTPError
        try:
            urlopen(base + "/requestz?request=absent", timeout=5)
            raise AssertionError("unknown id should 404")
        except HTTPError as e:
            assert e.code == 404
        try:
            urlopen(base + "/requestz?n=wat", timeout=5)
            raise AssertionError("bad n should 400")
        except HTTPError as e:
            assert e.code == 400
    finally:
        srv.stop()


def test_stitched_chrome_trace_pure_function():
    """Socket-free stitch: lanes offset by their wall epochs, args
    carry the id, a hop without t_wall still renders."""
    router_rec = {
        "id": "p-1", "outcome": "served", "t_wall": 100.0,
        "total_s": 0.3, "retries": 1, "deadline_ms": None,
        "attempts": [
            {"replica": "a:1", "t_off_s": 0.0, "latency_s": 0.05,
             "outcome": "ERR busy queue", "retried": True,
             "candidates": [{"replica": "a:1", "load": 0}]},
            {"replica": "b:2", "t_off_s": 0.06, "latency_s": 0.22,
             "outcome": "served"}]}
    hop = {"id": "p-1", "outcome": "served", "t_wall": 100.07,
           "total_s": 0.2, "ttft_s": 0.05,
           "phases": {"queue_wait": 0.01, "dispatch": 0.001,
                      "prefill": 0.04, "decode": 0.149},
           "recompiles": []}
    trace = routerd.stitched_chrome_trace(router_rec, [("b:2", hop)])
    xs = [t for t in trace["traceEvents"] if t.get("ph") == "X"]
    assert {t["pid"] for t in xs} == {0, 1}
    # the hop's lane is offset by its wall delta (70ms after accept)
    qw = next(t for t in xs if t["name"] == "queue_wait")
    assert abs(qw["ts"] - 70e3) < 1.0, qw
    route_span = next(t for t in xs if t["name"] == "route:served")
    assert route_span["ts"] == 0.0 and route_span["dur"] == 0.3e6
    assert all(t["args"]["request"] == "p-1" for t in xs)
    # router-lane-only view works too (no hops fetched)
    solo = routerd.route_chrome_trace(router_rec)
    assert {t["pid"] for t in solo["traceEvents"]
            if t.get("ph") == "X"} == {0}
