"""Property fuzz over random netconfig DAGs: for seeded random nets
built from the layer vocabulary, (a) inferred node shapes match the
actual forward values, (b) a train step leaves every parameter finite,
(c) the model checkpoint round-trips bitwise through a fresh trainer.
This is the generative counterpart of the per-layer unit tests — it
exercises layer COMPOSITIONS (conv stacks onto pools onto norms onto
branches) no hand-written case covers."""

import numpy as np
import jax
import pytest

from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.nnet.trainer import Trainer
from cxxnet_tpu.utils import serializer
from cxxnet_tpu.utils.config import parse_config_string

N_CLASS = 5


def _random_conf(rs):
    """A random conv/pool/act/norm trunk, optionally with
    inception-style split/concat blocks, ending flatten -> fullc ->
    softmax. Nodes are explicit integers so branches wire exactly."""
    lines = ["netconfig = start"]
    node = 0      # current output node id
    nxt = 1       # next unused node id
    c, h = 3, 16  # channels, spatial (square)

    def emit(src, dst, layer, *keys):
        lines.append("layer[%s->%s] = %s" % (src, dst, layer))
        lines.extend("  " + k for k in keys)

    for b in range(rs.randint(2, 6)):
        kind = rs.choice(["conv", "pool", "act", "norm", "branch"])
        if kind == "conv":
            k = int(rs.choice([1, 3])) if h >= 3 else 1
            ch = int(rs.choice([4, 8]))
            g = 2 if (k == 1 and c % 2 == 0 and rs.rand() < 0.3) else 1
            emit(node, nxt, "conv:c%d" % b, "kernel_size = %d" % k,
                 "pad = %d" % (k // 2), "nchannel = %d" % ch,
                 "ngroup = %d" % g, "random_type = xavier")
            node, nxt, c = nxt, nxt + 1, ch
        elif kind == "pool":
            if h < 4:
                continue
            emit(node, nxt, str(rs.choice(["max_pooling", "avg_pooling"])),
                 "kernel_size = 2", "stride = 2")
            node, nxt, h = nxt, nxt + 1, (h + 1) // 2
        elif kind == "act":
            emit(node, nxt, str(rs.choice(
                ["relu", "sigmoid", "tanh", "softplus", "prelu"])))
            node, nxt = nxt, nxt + 1
        elif kind == "norm":
            name = str(rs.choice(["batch_norm", "lrn"]))
            if name == "lrn":
                emit(node, nxt, name, "local_size = 3")
            else:
                emit(node, nxt, "batch_norm:bn%d" % b)
            node, nxt = nxt, nxt + 1
        elif kind == "branch" and h >= 3:
            a_in, b_in = nxt, nxt + 1
            emit(node, "%d,%d" % (a_in, b_in), "split")
            ca, cb = int(rs.choice([4, 8])), int(rs.choice([4, 8]))
            emit(a_in, nxt + 2, "conv:b%da" % b, "kernel_size = 1",
                 "nchannel = %d" % ca, "random_type = xavier")
            emit(b_in, nxt + 3, "conv:b%db" % b, "kernel_size = 3",
                 "pad = 1", "nchannel = %d" % cb, "random_type = xavier")
            emit("%d,%d" % (nxt + 2, nxt + 3), nxt + 4, "ch_concat")
            node, nxt, c = nxt + 4, nxt + 5, ca + cb
    emit(node, nxt, "flatten")
    node, nxt = nxt, nxt + 1
    emit(node, nxt, "fullc:head", "nhidden = %d" % N_CLASS,
         "init_sigma = 0.05")
    node = nxt
    lines.append("layer[%d->%d] = softmax" % (node, node))
    lines += ["netconfig = end", "input_shape = 3,16,16",
              "batch_size = 4", "eta = 0.05"]
    return "\n".join(lines) + "\n"


@pytest.mark.parametrize("seed", range(20))
def test_random_dag_shapes_grads_checkpoint(seed):
    rs = np.random.RandomState(100 + seed)
    conf = _random_conf(rs)
    # every generated config is valid by construction (the generator
    # tracks shape/channel/group constraints), so ANY init failure here
    # is a framework regression — no except-and-skip
    tr = Trainer()
    for k, v in parse_config_string(conf):
        tr.set_param(k, v)
    tr.init_model()
    net = tr.net

    # (a) inferred shapes match actual forward values on every node
    x = rs.rand(4, 3, 16, 16).astype(np.float32)
    values, _ = net.forward(tr.params, x, train=False,
                            rng=jax.random.PRNGKey(0))
    for n, v in enumerate(values):
        if v is None:
            continue
        want = tuple(net.node_shapes[n][1:])
        got = tuple(np.shape(v)[1:])
        assert got == want, "node %d: inferred %s actual %s\n%s" % (
            n, want, got, conf)

    # (b) one update step: finite params after
    b = DataBatch()
    b.data = x
    b.label = rs.randint(0, N_CLASS, (4, 1)).astype(np.float32)
    b.batch_size = 4
    tr.update(b)
    for p in tr.params:
        for key, w in p.items():
            assert np.isfinite(np.asarray(w)).all(), (key, conf)

    # (c) checkpoint round-trip is bitwise through a fresh trainer
    w1 = serializer.Writer()
    tr.save_model(w1)
    tr2 = Trainer()
    for k, v in parse_config_string(conf):
        tr2.set_param(k, v)
    tr2.init_model()
    tr2.load_model(serializer.Reader(w1.getvalue()))
    w2 = serializer.Writer()
    tr2.save_model(w2)
    assert w1.getvalue() == w2.getvalue(), conf


# --- serving fuzz: decode == full recompute across the attention grid --

ATT_GRID = [
    # (embed_extra, attn_extra) random-ish corners beyond the
    # hand-picked cases in test_decode.py
    ("pos_embed = 1", "  nkvhead = 2\n"),
    ("pos_embed = 0", "  rope = 1\n"),
    ("pos_embed = 0", "  rope = 1\n  attn_window = 5\n"),
    ("pos_embed = 1", "  nkvhead = 1\n  attn_window = 9\n"),
    ("pos_embed = 0", "  rope = 1\n  nkvhead = 4\n"),
    ("pos_embed = 1", "  attn_window = 16\n"),
    # flash-decode (decode_chunk while-loop) corners: chunk dividing and
    # equal to the cache length, composed with GQA/rope/window
    ("pos_embed = 1", "  decode_chunk = 8\n  nkvhead = 2\n"),
    ("pos_embed = 0",
     "  rope = 1\n  attn_window = 5\n  decode_chunk = 8\n"),
    ("pos_embed = 1", "  decode_chunk = 24\n"),
    ("pos_embed = 0",
     "  rope = 1\n  nkvhead = 4\n  decode_chunk = 12\n"),
]


# tier-1 budget: three representative corners ride tier-1 — plain GQA
# (0), rope+window (2), and the flash-decode chunk composed with
# rope+window (7); the full grid still runs in the slow tier
@pytest.mark.parametrize(
    "case",
    [c if c in (0, 2, 7) else pytest.param(c, marks=pytest.mark.slow)
     for c in range(len(ATT_GRID))])
def test_decode_grid_matches_recompute(case):
    """KV-cached decode must be token-exact vs full-prefix recompute for
    every (positions, rope, GQA-width, window) corner — including ragged
    prompts — not just the hand-picked combinations."""
    from tests.test_decode import _trained, _check
    embed_extra, attn_extra = ATT_GRID[case]
    tr = _trained(embed_extra=embed_extra, attn_extra=attn_extra,
                  steps=8)
    _check(tr, n_new=6)
    # beam=1 IS greedy, for every attention-config corner
    rsb = np.random.RandomState(90 + case)
    bp = rsb.randint(0, 12, (4, 6))
    np.testing.assert_array_equal(tr.beam_generate(bp, 5, beam=1),
                                  tr.generate(bp, 5))
    # ragged variant on the same trainer
    rs = np.random.RandomState(50 + case)
    prompts = rs.randint(0, 12, (4, 8))
    lens = np.array([4, 8, 6, 5])
    got = tr.generate(prompts, 4, prompt_lens=lens)
    for r in range(4):
        want = tr.generate(prompts[r:r + 1, :lens[r]], 4)
        np.testing.assert_array_equal(got[r:r + 1], want,
                                      err_msg="row %d" % r)


# --- parallelism fuzz: random DAG x (dp, dp x tp) exactness ------------


@pytest.mark.parametrize("seed", range(6))
def test_random_dag_parallel_matches_single_device(seed):
    """Seeded random DAGs must train IDENTICALLY (tight tolerance)
    under data parallelism and composed dp x tp vs the single-device
    net — the generative version of test_compose's hand-built cases."""
    rs = np.random.RandomState(300 + seed)
    conf = _random_conf(rs)
    # batch 8 so every data-parallel degree divides it
    variants = {
        "1dev": "dev = cpu\nbatch_size = 8\n",
        "dp8": "dev = cpu:0-7\nbatch_size = 8\n",
        "dp4xtp2": "dev = cpu:0-7\nbatch_size = 8\n"
                   "model_parallel = 2\n",
    }
    from tests.test_compose import _trainer, _assert_params_match
    trainers = {name: _trainer(conf, extra)
                for name, extra in variants.items()}
    xs = rs.rand(3, 8, 3, 16, 16).astype(np.float32)
    ys = rs.randint(0, N_CLASS, (3, 8, 1)).astype(np.float32)
    for x, y in zip(xs, ys):
        for tr in trainers.values():
            b = DataBatch()
            b.data = x
            b.label = y
            b.batch_size = 8
            tr.update(b)
    ref = trainers["1dev"]
    for name in ("dp8", "dp4xtp2"):
        # same helper + 2e-4 tolerance every sibling dp/tp exactness
        # comparison uses (all-reduce ordering drift allowance)
        _assert_params_match(trainers[name], ref)


@pytest.mark.parametrize("seed", range(8))
def test_random_dag_pipeline_matches_single_device(seed):
    """Random DAGs under pipeline parallelism track the single-device
    net. Two documented semantic boundaries shape the comparison
    (doc/multichip.md): batch_norm statistics are per-MICROBATCH under
    GPipe (exact only at pipeline_micro = 1) and per-data-SHARD under a
    composed dp axis (exact only at dp = 1) — so BN nets run pp2-only
    with one microbatch, everything else runs pp2 x dp4 with the
    default microbatch count."""
    rs = np.random.RandomState(300 + seed)
    conf = _random_conf(rs)
    from tests.test_compose import _trainer, _assert_params_match
    if "batch_norm" in conf:
        extra = ("dev = cpu:0-1\nbatch_size = 8\n"
                 "pipeline_parallel = 2\npipeline_micro = 1\n")
    else:
        extra = ("dev = cpu:0-7\nbatch_size = 8\n"
                 "pipeline_parallel = 2\n")
    tr = _trainer(conf, extra)
    ref = _trainer(conf, "dev = cpu\nbatch_size = 8\n")
    assert tr._pp_entries is not None
    xs = rs.rand(2, 8, 3, 16, 16).astype(np.float32)
    ys = rs.randint(0, N_CLASS, (2, 8, 1)).astype(np.float32)
    for x, y in zip(xs, ys):
        for t in (tr, ref):
            b = DataBatch()
            b.data = x
            b.label = y
            b.batch_size = 8
            t.update(b)
    _assert_params_match(tr, ref)


SP_ATT_CONF = """
netconfig = start
layer[+1:att] = attention:att
  nhead = 4
  causal = 1
  init_sigma = 0.1
%s
layer[+1] = flatten
layer[+1:fc1] = fullc:fc1
  nhidden = 8
  init_sigma = 0.1
layer[+0] = softmax
netconfig = end
input_shape = 8,1,16
batch_size = 8
eta = 0.1
"""

SP_GRID = [
    "  nkvhead = 2\n",
    "  rope = 1\n",
    "  rope = 1\n  attn_window = 8\n",
    "  attn_window = 16\n",
    "  rope = 1\n  nkvhead = 4\n",
]


@pytest.mark.parametrize("case", range(len(SP_GRID)))
def test_attention_grid_seq_parallel_matches(case):
    """Ring attention under seq_parallel = 2 trains identically to the
    single-device net across the (GQA-width, rope, window) grid — the
    sp counterpart of the decode grid above (window tile-skipping and
    GQA-sized ring hops are the risky corners)."""
    from tests.test_compose import _trainer, _assert_params_match
    conf = SP_ATT_CONF % SP_GRID[case]
    tr = _trainer(conf, "dev = cpu:0-7\nseq_parallel = 2\n")
    ref = _trainer(conf, "dev = cpu\n")
    assert "sp" in tr.mesh.axis_names
    rs = np.random.RandomState(case)
    for _ in range(3):
        b = DataBatch()
        b.data = rs.rand(8, 8, 1, 16).astype(np.float32)
        b.label = rs.randint(0, 8, (8, 1)).astype(np.float32)
        b.batch_size = 8
        tr.update(b)
        ref.update(b)
    _assert_params_match(tr, ref)


EP_CONF = """
netconfig = start
layer[+1:m1] = moe:m1
  nexpert = %d
  nhidden = 8
%s
  init_sigma = 0.1
layer[+1] = relu
layer[+1:fc] = fullc:fc
  nhidden = 4
  init_sigma = 0.1
layer[+0] = softmax
netconfig = end
input_shape = 1,1,6
batch_size = 8
eta = 0.1
"""

EP_GRID = [(4, ""), (8, ""), (4, "  top_k = 2\n"), (8, "  top_k = 1\n")]


@pytest.mark.parametrize("case", range(len(EP_GRID)))
def test_moe_grid_expert_parallel_matches(case):
    """Expert parallelism across the (nexpert, top_k) grid: sharded
    experts + gate-weighted psum combine must train identically to the
    single-device dense dispatch."""
    from tests.test_compose import _trainer, _assert_params_match
    nexpert, extra_keys = EP_GRID[case]
    conf = EP_CONF % (nexpert, extra_keys)
    tr = _trainer(conf, "dev = cpu:0-7\nexpert_parallel = 2\n")
    ref = _trainer(conf, "dev = cpu\n")
    assert "ep" in tr.mesh.axis_names
    rs = np.random.RandomState(40 + case)
    for _ in range(3):
        b = DataBatch()
        b.data = rs.rand(8, 1, 1, 6).astype(np.float32)
        b.label = rs.randint(0, 4, (8, 1)).astype(np.float32)
        b.batch_size = 8
        tr.update(b)
        ref.update(b)
    _assert_params_match(tr, ref)


@pytest.mark.parametrize("seed", range(4))
def test_random_dag_zero_sharding_matches(seed):
    """ZeRO tiers on random DAGs: update_on_server (opt-state sharding)
    and fsdp (ZeRO-3 full param sharding) must not change numerics vs
    plain data parallelism."""
    rs = np.random.RandomState(500 + seed)
    conf = _random_conf(rs)
    from tests.test_compose import _trainer, _assert_params_match
    variants = {
        "1dev": "dev = cpu\nbatch_size = 8\n",
        "zero1": "dev = cpu:0-7\nbatch_size = 8\nupdate_on_server = 1\n",
        "fsdp": "dev = cpu:0-7\nbatch_size = 8\nfsdp = 1\n",
    }
    trainers = {name: _trainer(conf, extra)
                for name, extra in variants.items()}
    xs = rs.rand(3, 8, 3, 16, 16).astype(np.float32)
    ys = rs.randint(0, N_CLASS, (3, 8, 1)).astype(np.float32)
    for x, y in zip(xs, ys):
        for tr in trainers.values():
            b = DataBatch()
            b.data = x
            b.label = y
            b.batch_size = 8
            tr.update(b)
    for name in ("zero1", "fsdp"):
        _assert_params_match(trainers[name], trainers["1dev"])


@pytest.mark.parametrize("seed", range(15))
def test_mutated_config_fails_controlled(seed):
    """Corrupted configs must fail with a framework error (ValueError /
    ConfigError / AssertionError with a message), never an uncontrolled
    crash — the reference's utils::Check discipline (src/utils/utils.h)
    applied generatively: take a valid random config and break it."""
    rs = np.random.RandomState(700 + seed)
    conf = _random_conf(rs)
    lines = conf.splitlines()
    mutation = rs.choice(["drop", "scramble_node", "bad_value", "dup"])
    idx = [i for i, l in enumerate(lines) if l.startswith("layer[")]
    i = int(rs.choice(idx))
    if mutation == "drop":
        del lines[i]                       # dangling node references
    elif mutation == "scramble_node":
        lines[i] = lines[i].replace("[", "[9", 1)   # undefined source
    elif mutation == "bad_value":
        lines.insert(i + 1, "  kernel_size = -3")
    elif mutation == "dup":
        lines.insert(i, lines[i])          # node written twice
    broken = "\n".join(lines) + "\n"
    tr = Trainer()
    try:
        for k, v in parse_config_string(broken):
            tr.set_param(k, v)
        tr.init_model()
        # some mutations still yield a valid net (e.g. a dup split
        # branch that type-checks) — then it must actually train
        b = DataBatch()
        b.data = rs.rand(4, 3, 16, 16).astype(np.float32)
        b.label = rs.randint(0, N_CLASS, (4, 1)).astype(np.float32)
        b.batch_size = 4
        tr.update(b)
    except (ValueError, AssertionError) as e:
        # 40-seed census: every failure is a messaged ValueError
        # (ConfigError subclasses it); KeyError/IndexError would be an
        # uncontrolled-crash regression
        assert str(e), "error must carry a message"
