"""Serving-artifact version guard (utils/artifact.py): stale, corrupt,
swapped, or cross-export files must fail with a framework message, not
with whatever jax.export.deserialize does to alien bytes (the
reference's model-blob version check, nnet_config.h:126-145)."""

import struct

import numpy as np
import pytest

from cxxnet_tpu.utils import artifact


def test_frame_roundtrip():
    meta = {"cache_fingerprint": "abc", "batch": 4}
    data = artifact.frame("decode_step", meta, b"PAYLOAD")
    got_meta, payload = artifact.unframe(data, "decode_step")
    assert payload == b"PAYLOAD"
    assert got_meta["cache_fingerprint"] == "abc"
    assert got_meta["batch"] == 4 and got_meta["kind"] == "decode_step"


def test_stale_unversioned_artifact_rejected():
    with pytest.raises(ValueError, match="pre-versioning|bad magic"):
        artifact.unframe(b"MHLO...raw stablehlo bytes...", "forward")


def test_future_version_rejected():
    data = artifact.frame("forward", {}, b"x")
    bumped = data[:4] + struct.pack("<I", artifact.VERSION + 1) + data[8:]
    with pytest.raises(ValueError, match="newer than this framework"):
        artifact.unframe(bumped, "forward")


def test_kind_mismatch_rejected():
    data = artifact.frame("decode_prefill", {}, b"x")
    with pytest.raises(ValueError, match="kind mismatch"):
        artifact.unframe(data, "decode_step")


def test_truncated_header_rejected():
    data = artifact.frame("forward", {"k": 1}, b"x")
    with pytest.raises(ValueError, match="truncated"):
        artifact.unframe(data[:14], "forward")


def test_cache_fingerprint_sensitivity():
    base = artifact.cache_fingerprint(
        ["c0:k", "c0:v"], [(2, 4, 16, 8), (2, 4, 16, 8)], "bfloat16")
    assert base == artifact.cache_fingerprint(
        ["c0:k", "c0:v"], [(2, 4, 16, 8), (2, 4, 16, 8)], "bfloat16")
    assert base != artifact.cache_fingerprint(
        ["c0:k", "c0:v"], [(2, 4, 32, 8), (2, 4, 32, 8)], "bfloat16")
    assert base != artifact.cache_fingerprint(
        ["c0:k", "c0:v"], [(2, 4, 16, 8), (2, 4, 16, 8)], "float32")


def test_load_decode_refuses_cross_export_pair(tmp_path):
    """Integration: pairing the prefill of one export with the step of a
    DIFFERENT cache layout fails with the fingerprint message."""
    from cxxnet_tpu import api
    p1 = tmp_path / "pre.hlo"
    p2 = tmp_path / "step.hlo"
    p1.write_bytes(artifact.frame(
        "decode_prefill", {"cache_fingerprint": "aaa"}, b"x"))
    p2.write_bytes(artifact.frame(
        "decode_step", {"cache_fingerprint": "bbb"}, b"y"))
    with pytest.raises(ValueError, match="different exports"):
        api.load_decode(str(p1), str(p2))
