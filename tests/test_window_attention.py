"""Sliding-window (local) attention — `attn_window` on the attention layer,
`window=` on every attention path (dense reference, single-chip flash,
XLA ring, flash ring, ulysses). Causal-only by contract.
"""

import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh

from cxxnet_tpu import ops
from cxxnet_tpu.parallel import ring

W = 96  # window under one tile (exercises partial masks)


def _qkv(b=1, h=2, s=512, d=16, seed=0):
    rs = np.random.RandomState(seed)
    mk = lambda: rs.randn(b, h, s, d).astype(np.float32)
    return mk(), mk(), mk()


def _manual_window(q, k, v, window):
    s_ = jnp.einsum("bhqd,bhkd->bhqk", q, k) * (q.shape[-1] ** -0.5)
    L = q.shape[2]
    qpos = np.arange(L)[:, None]
    kpos = np.arange(L)[None, :]
    keep = (qpos >= kpos) & (qpos - kpos < window)
    s_ = jnp.where(jnp.asarray(keep), s_, -jnp.inf)
    p = jax.nn.softmax(s_, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def test_reference_window():
    q, k, v = _qkv(seed=1)
    out = ring.attention_reference(q, k, v, causal=True, window=W)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_manual_window(q, k, v, W)),
                               rtol=1e-5, atol=1e-6)


def test_flash_window_matches_reference():
    q, k, v = _qkv(seed=2)
    out = ops.flash_attention(jnp.asarray(q), jnp.asarray(k),
                              jnp.asarray(v), causal=True, window=W)
    ref = ring.attention_reference(q, k, v, causal=True, window=W)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_flash_window_grads():
    q, k, v = _qkv(seed=3)
    w = np.random.RandomState(7).randn(*q.shape).astype(np.float32)
    gf = jax.grad(lambda q_: jnp.sum(ops.flash_attention(
        q_, k, v, causal=True, window=W) * w))(jnp.asarray(q))
    gr = jax.grad(lambda q_: jnp.sum(ring.attention_reference(
        q_, k, v, causal=True, window=W) * w))(jnp.asarray(q))
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                               rtol=3e-4, atol=3e-4)


def _mesh(n=4):
    return Mesh(np.array(jax.devices()[:n]), ("sp",))


def test_ring_xla_window():
    q, k, v = _qkv(seed=4)
    out = ring.ring_attention(q, k, v, _mesh(), causal=True, window=W)
    ref = ring.attention_reference(q, k, v, causal=True, window=W)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_ring_flash_window():
    os.environ["CXXNET_RING"] = "flash"
    ops.set_use_pallas(True)
    try:
        q, k, v = _qkv(seed=5)
        out = ring.ring_attention(q, k, v, _mesh(), causal=True, window=W)
    finally:
        ops.set_use_pallas(None)
        os.environ.pop("CXXNET_RING", None)
    ref = ring.attention_reference(q, k, v, causal=True, window=W)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_ulysses_window():
    q, k, v = _qkv(h=8, seed=6)
    out = ring.ulysses_attention(q, k, v, _mesh(), causal=True, window=W)
    ref = ring.attention_reference(q, k, v, causal=True, window=W)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_layer_attn_window_requires_causal():
    from cxxnet_tpu.layer import factory
    lay = factory.create_layer(factory.get_layer_type("attention"))
    lay.set_param("nhead", "2")
    lay.set_param("attn_window", "8")
    with pytest.raises(ValueError):
        lay.infer_shape([(2, 16, 1, 32)])


def test_layer_window_matches_reference():
    from cxxnet_tpu.layer import factory
    from cxxnet_tpu.layer.base import ApplyContext
    d, nh, L, b = 16, 2, 32, 2
    lay = factory.create_layer(factory.get_layer_type("attention"))
    lay.set_param("nhead", str(nh))
    lay.set_param("causal", "1")
    lay.set_param("attn_window", "8")
    lay.infer_shape([(b, d, 1, L)])
    rs = np.random.RandomState(0)
    params = {k_: jnp.asarray(v_)
              for k_, v_ in lay.init_params(rs).items()}
    x = rs.randn(b, d, 1, L).astype(np.float32)
    (out,) = lay.apply(params, [jnp.asarray(x)], ApplyContext(train=False))
    # manual: same weights, windowed reference attention
    dh = d // nh
    seq = x.reshape(b, d, L).transpose(0, 2, 1)
    qkv = np.asarray(seq @ params["wqkv"])
    q, k, v = np.split(qkv, 3, axis=-1)
    hd = lambda t: t.reshape(b, L, nh, dh).transpose(0, 2, 1, 3)
    att = ring.attention_reference(
        jnp.asarray(hd(q)), jnp.asarray(hd(k)), jnp.asarray(hd(v)),
        causal=True, window=8)
    ref = (np.asarray(att).transpose(0, 2, 1, 3).reshape(b, L, d)
           @ np.asarray(params["wo"])).transpose(0, 2, 1).reshape(b, d, 1, L)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-5)


def test_flash_window_with_skipped_tiles():
    """L=768 (three 256-tiles) with window=96: the (q_blk=2, kv_blk=0)
    tile is entirely out of window and must be statically skipped —
    exercises _block_needed's window branch, not just the mask."""
    q, k, v = _qkv(s=768, seed=8)
    out = ops.flash_attention(jnp.asarray(q), jnp.asarray(k),
                              jnp.asarray(v), causal=True, window=W)
    ref = ring.attention_reference(q, k, v, causal=True, window=W)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
    w = np.random.RandomState(3).randn(*q.shape).astype(np.float32)
    gf = jax.grad(lambda q_: jnp.sum(ops.flash_attention(
        q_, k, v, causal=True, window=W) * w))(jnp.asarray(q))
    gr = jax.grad(lambda q_: jnp.sum(ring.attention_reference(
        q_, k, v, causal=True, window=W) * w))(jnp.asarray(q))
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                               rtol=3e-4, atol=3e-4)


@pytest.mark.slow
def test_ring_flash_window_with_skipped_blocks():
    """8-device ring at L=1024, window=96: most ring steps hold blocks
    entirely out of window (skipped by the traced tile predicate) and the
    result must still match the dense reference, incl. grads."""
    os.environ["CXXNET_RING"] = "flash"
    ops.set_use_pallas(True)
    try:
        q, k, v = _qkv(s=1024, seed=9)
        mesh = _mesh(8)
        out = ring.ring_attention(q, k, v, mesh, causal=True, window=W)
        ref = ring.attention_reference(q, k, v, causal=True, window=W)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)
        w = np.random.RandomState(4).randn(*q.shape).astype(np.float32)
        gf = jax.grad(lambda q_: jnp.sum(ring.ring_attention(
            q_, k, v, mesh, causal=True, window=W) * w))(jnp.asarray(q))
    finally:
        ops.set_use_pallas(None)
        os.environ.pop("CXXNET_RING", None)
    gr = jax.grad(lambda q_: jnp.sum(ring.attention_reference(
        q_, k, v, causal=True, window=W) * w))(jnp.asarray(q))
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                               rtol=3e-4, atol=3e-4)


def test_layer_negative_window_rejected():
    from cxxnet_tpu.layer import factory
    lay = factory.create_layer(factory.get_layer_type("attention"))
    lay.set_param("nhead", "2")
    lay.set_param("causal", "1")
    lay.set_param("attn_window", "-4096")
    with pytest.raises(ValueError):
        lay.infer_shape([(2, 16, 1, 32)])


def test_trainer_sp_window_e2e():
    """DSL attention with attn_window under seq_parallel=2: a train step
    runs and the eval forward matches the single-device windowed net."""
    from cxxnet_tpu.models import transformer_lm_netconfig
    from cxxnet_tpu.nnet.trainer import Trainer
    from cxxnet_tpu.utils.config import parse_config_string
    from cxxnet_tpu.io.data import DataBatch

    conf = transformer_lm_netconfig(40, dim=32, nhead=4, nlayer=1)
    conf = conf.replace("  causal = 1\n",
                        "  causal = 1\n  attn_window = 16\n")
    base = (conf + "input_shape = 1,1,64\nbatch_size = 4\n"
            "label_vec[0,64) = label\nupdater = adam\neta = 0.003\n"
            "eval_train = 0\n")
    rs = np.random.RandomState(0)
    x = rs.randint(0, 40, (4, 1, 1, 64)).astype(np.float32)
    y = rs.randint(0, 40, (4, 64)).astype(np.float32)
    losses = []
    for dev_extra in ("dev = cpu\n", "dev = cpu:0-1\nseq_parallel = 2\n"):
        tr = Trainer()
        for k_, v_ in parse_config_string(base + dev_extra):
            tr.set_param(k_, v_)
        tr.init_model()
        b = DataBatch()
        b.data, b.label, b.batch_size = x, y, 4
        tr.update(b)
        li = tr.net.label_info_from(y)
        _, loss = tr.net.forward(tr.params, x, labels=li, train=False,
                                 mesh=tr.mesh)
        losses.append(float(loss))
    np.testing.assert_allclose(losses[0], losses[1], rtol=1e-4)
