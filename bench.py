"""Benchmark harness for the BASELINE configs.

Default (no args): AlexNet ImageNet-shape training throughput — prints ONE
JSON line {"metric", "value", "unit", "vs_baseline"} for the driver.
Baseline target (BASELINE.md): 2000 images/sec/chip on AlexNet.

`python bench.py all` additionally benches the other BASELINE configs
(GoogLeNet, MNIST MLP/conv, kaggle_bowl-shaped net), one JSON line each —
the AlexNet headline line is always printed LAST so drivers reading the
final line see the headline metric.

`python bench.py pipeline` benches the END-TO-END input pipeline: a real
JPEG imgbinx corpus is packed on the fly and AlexNet trains from
imgbinx -> decode pool -> augment -> threadbuffer, measuring pipeline-fed
img/s next to (a) the device-resident synthetic number and (b) the
io-only rate (iterating without training — the reference's test_io mode,
src/cxxnet_main.cpp:363-376). NOTE the sandbox has ONE host core: the
decode pool cannot exhibit host parallelism here, so pipeline-fed
throughput reflects single-core JPEG decode, not the framework ceiling; on
a real TPU VM host (tens to hundreds of cores) the pool scales decode
until the chip is the bottleneck. The io-only line tells you which side
bound the run.

Measures the steady-state train step (forward + backward + SGD update) with
device-resident input — the input pipeline overlaps H2D via the
threadbuffer prefetcher in real training, and per-step train metrics are
off (eval_train=0) as they would be for a throughput run. bf16 mixed
precision (the TPU-native recipe). The final value fetch forces a full
device sync so async dispatch cannot inflate the number
(block_until_ready does not sync through the axon tunnel).
"""

import json
import os
import sys
import time

import numpy as np


def _timed_rate(tr, b, steps, units_per_step):
    """Shared measurement protocol: 3-step warmup, then two timed passes
    reporting the better — shared-chip contention skews single runs by
    +-20% and the steady-state rate is the meaningful one. The sync is a
    value-fetch of the first param tensor (first layer may be weightless),
    which forces a sync through the tunnel (block_until_ready does not)."""
    import jax.numpy as jnp

    def sync():
        float(jnp.sum(next(v for p in tr.params for v in p.values())))

    for _ in range(3):
        tr.update(b)
    sync()
    best = 0.0
    for _ in range(2):
        t0 = time.perf_counter()
        for _ in range(steps):
            tr.update(b)
        sync()
        best = max(best, steps * units_per_step
                   / (time.perf_counter() - t0))
    return best


def _throughput(tr, shape, nclass, batch, steps=30):
    import jax
    from cxxnet_tpu.io.data import DataBatch

    rs = np.random.RandomState(0)
    b = DataBatch()
    b.data = jax.device_put(rs.rand(batch, *shape).astype(np.float32))
    b.label = jax.device_put(
        rs.randint(0, nclass, (batch, 1)).astype(np.float32))
    b.batch_size = batch
    return _timed_rate(tr, b, steps, batch)


BF16 = "eval_train = 0\ncompute_dtype = bfloat16\n"


def bench_alexnet():
    from cxxnet_tpu.models import alexnet_trainer
    batch = 256
    tr = alexnet_trainer(batch_size=batch, input_hw=227, dev="tpu",
                         extra_cfg=BF16)
    ips = _throughput(tr, (3, 227, 227), 1000, batch)
    return {
        "metric": "alexnet_imagenet_images_per_sec_per_chip",
        "value": round(ips, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(ips / 2000.0, 4),
    }


def bench_alexnet_b1024():
    """Large-batch variant: fills the MXU better (measured ~18.3k img/s on
    v5e). Kept as a secondary line; the batch-256 headline stays the
    cross-round comparable (the reference recipe's batch,
    example/ImageNet/ImageNet.conf)."""
    from cxxnet_tpu.models import alexnet_trainer
    batch = 1024
    tr = alexnet_trainer(batch_size=batch, input_hw=227, dev="tpu",
                         extra_cfg=BF16)
    ips = _throughput(tr, (3, 227, 227), 1000, batch, steps=15)
    return {"metric": "alexnet_imagenet_b1024_images_per_sec_per_chip",
            "value": round(ips, 2), "unit": "images/sec/chip",
            "vs_baseline": round(ips / 2000.0, 4)}


def bench_googlenet():
    from cxxnet_tpu.models import googlenet_trainer
    batch = 128
    tr = googlenet_trainer(batch_size=batch, input_hw=224, dev="tpu",
                           extra_cfg=BF16)
    ips = _throughput(tr, (3, 224, 224), 1000, batch)
    return {"metric": "googlenet_imagenet_images_per_sec_per_chip",
            "value": round(ips, 2), "unit": "images/sec/chip",
            "vs_baseline": round(ips / 2000.0, 4)}


def bench_googlenet_b256():
    """Large-batch inception variant: the b128 headline under-fills the
    MXU on the narrow tower convs (22.7% MFU, tools/roofline.py); doubling
    the batch doubles the per-tower matmul rows at constant weight
    traffic. Secondary line — b128 stays the cross-round comparable."""
    from cxxnet_tpu.models import googlenet_trainer
    batch = 256
    tr = googlenet_trainer(batch_size=batch, input_hw=224, dev="tpu",
                           extra_cfg=BF16)
    ips = _throughput(tr, (3, 224, 224), 1000, batch, steps=15)
    return {"metric": "googlenet_imagenet_b256_images_per_sec_per_chip",
            "value": round(ips, 2), "unit": "images/sec/chip",
            "vs_baseline": round(ips / 2000.0, 4)}


def bench_resnet():
    from cxxnet_tpu.models import resnet_trainer
    batch = 128
    tr = resnet_trainer(batch_size=batch, input_hw=224, dev="tpu",
                        extra_cfg=BF16)
    ips = _throughput(tr, (3, 224, 224), 1000, batch)
    # no reference baseline: the family postdates the reference
    return {"metric": "resnet18_imagenet_images_per_sec_per_chip",
            "value": round(ips, 2), "unit": "images/sec/chip",
            "vs_baseline": None}


def bench_mobilenet():
    from cxxnet_tpu.models import mobilenet_trainer
    batch = 256
    tr = mobilenet_trainer(batch_size=batch, input_hw=224, dev="tpu",
                           extra_cfg=BF16)
    ips = _throughput(tr, (3, 224, 224), 1000, batch)
    # no reference baseline: depthwise separability postdates the ref
    return {"metric": "mobilenet_imagenet_images_per_sec_per_chip",
            "value": round(ips, 2), "unit": "images/sec/chip",
            "vs_baseline": None}


def bench_vgg():
    from cxxnet_tpu.models import vgg_trainer
    batch = 64
    tr = vgg_trainer(batch_size=batch, input_hw=224, dev="tpu",
                     remat=1, extra_cfg=BF16)
    ips = _throughput(tr, (3, 224, 224), 1000, batch)
    # no reference baseline: VGG postdates the reference's example set
    return {"metric": "vgg16_imagenet_images_per_sec_per_chip",
            "value": round(ips, 2), "unit": "images/sec/chip",
            "vs_baseline": None}


def _conf_trainer(netconfig, shape, batch, extra=""):
    from cxxnet_tpu.nnet.trainer import Trainer
    from cxxnet_tpu.utils.config import parse_config_string
    conf = (netconfig +
            "input_shape = %s\n" % ",".join(str(s) for s in shape) +
            "batch_size = %d\ndev = tpu\neta = 0.1\n" % batch + extra)
    tr = Trainer()
    for k, v in parse_config_string(conf):
        tr.set_param(k, v)
    tr.init_model()
    return tr


MNIST_MLP = """
netconfig = start
layer[+1] = fullc:fc1
  nhidden = 100
  init_sigma = 0.01
layer[+1] = sigmoid
layer[+1] = fullc:fc2
  nhidden = 10
  init_sigma = 0.01
layer[+0] = softmax
netconfig = end
"""

MNIST_CONV = """
netconfig = start
layer[0->1] = conv:cv1
  kernel_size = 3
  pad = 1
  stride = 2
  nchannel = 32
  random_type = xavier
layer[1->2] = max_pooling
  kernel_size = 3
  stride = 2
layer[2->3] = flatten
layer[3->3] = dropout
  threshold = 0.5
layer[3->4] = fullc:fc1
  nhidden = 100
  init_sigma = 0.01
layer[4->5] = sigmoid
layer[5->6] = fullc:fc2
  nhidden = 10
  init_sigma = 0.01
layer[6->6] = softmax
netconfig = end
"""

BOWL = """
netconfig = start
layer[0->1] = conv:c1
  kernel_size = 5
  nchannel = 32
  random_type = xavier
layer[1->2] = relu
layer[2->3] = max_pooling
  kernel_size = 3
  stride = 2
layer[3->4] = conv:c2
  kernel_size = 3
  nchannel = 64
  random_type = xavier
layer[4->5] = relu
layer[5->6] = max_pooling
  kernel_size = 3
  stride = 2
layer[6->7] = flatten
layer[7->8] = fullc:f1
  nhidden = 256
  random_type = xavier
layer[8->9] = relu
layer[9->10] = fullc:f2
  nhidden = 121
  random_type = xavier
layer[10->10] = softmax
netconfig = end
"""


def _bench_lm(metric, L, batch, steps, attn_extra=""):
    """Shared LM bench harness: build the L-long decoder (vocab 8192,
    dim 512, 8 heads, 4 blocks), feed a device-resident random token
    batch, report tokens/sec via the common _timed_rate protocol."""
    import jax
    from cxxnet_tpu.models import transformer_lm_trainer
    from cxxnet_tpu.io.data import DataBatch
    tr = transformer_lm_trainer(
        vocab=8192, seq=L, batch_size=batch, dim=512, nhead=8, nlayer=4,
        dev="tpu", extra_cfg=BF16, attn_extra=attn_extra)
    rs = np.random.RandomState(0)
    b = DataBatch()
    b.data = jax.device_put(
        rs.randint(0, 8192, (batch, 1, 1, L)).astype(np.float32))
    b.label = jax.device_put(
        rs.randint(0, 8192, (batch, L)).astype(np.float32))
    b.batch_size = batch
    best = _timed_rate(tr, b, steps=steps, units_per_step=batch * L)
    return {"metric": metric, "value": round(best, 1),
            "unit": "tokens/sec/chip", "vs_baseline": None}


def bench_vit():
    """ViT-S/16-shaped (224x224, patch 16, dim 384, 12 blocks, 6 heads)
    training throughput — the DSL-composed vision-transformer family
    (patch-embed conv -> im2seq -> RoPE attention blocks); no reference
    baseline (the family postdates the reference)."""
    from cxxnet_tpu.models import vit_trainer
    batch = 128
    tr = vit_trainer(n_class=1000, image_hw=224, patch=16, dim=384,
                     nhead=6, nlayer=12, ffn_mult=4, batch_size=batch,
                     dev="tpu", extra_cfg=BF16)
    ips = _throughput(tr, (3, 224, 224), 1000, batch, steps=15)
    return {"metric": "vit_s16_images_per_sec_per_chip",
            "value": round(ips, 2), "unit": "images/sec/chip",
            "vs_baseline": None}


def bench_transformer_lm():
    """Long-context LM training throughput: tokens/sec at L=2048 bf16
    (flash attention path; no reference baseline — the reference is a CNN
    framework with no sequence axis, SURVEY.md §5)."""
    return _bench_lm("transformer_lm_L2048_tokens_per_sec_per_chip",
                     L=2048, batch=8, steps=20)


def bench_transformer_lm_long():
    """Long-context recipe: L=8192 bf16 with GQA (nkvhead=2), sliding
    window 1024, and RoPE — the flash-attention + window path end to end
    (no reference baseline; the reference is a CNN framework). Measured
    164,261 tokens/s/chip on v5lite (ROUND_NOTES.md)."""
    return _bench_lm(
        "transformer_lm_L8192_gqa_window_tokens_per_sec_per_chip",
        L=8192, batch=2, steps=10,
        attn_extra="nkvhead = 2\nattn_window = 1024\nrope = 1\n")


def bench_alexnet_infer():
    """Inference throughput (the reference's `pred` task shape): forward
    + on-device argmax via predict_device, batch 256 bf16. Calls are
    chained with ONE value-fetch sync per timed pass — the serving-loop
    regime (results stay on device; a per-call host fetch would measure
    the tunnel RPC, which bench_alexnet_latency_b1 covers)."""
    import jax
    import jax.numpy as jnp
    from cxxnet_tpu.models import alexnet_trainer
    from cxxnet_tpu.io.data import DataBatch
    batch = 256
    tr = alexnet_trainer(batch_size=batch, input_hw=227, dev="tpu",
                         extra_cfg=BF16)
    rs = np.random.RandomState(0)
    b = DataBatch()
    b.data = jax.device_put(rs.rand(batch, 3, 227, 227).astype(np.float32))
    b.label = jax.device_put(np.zeros((batch, 1), np.float32))
    b.batch_size = batch
    out = None
    for _ in range(3):
        out = tr.predict_device(b)
    float(jnp.sum(out))
    best = 0.0
    for _ in range(2):
        t0 = time.perf_counter()
        n = 20
        for _ in range(n):
            out = tr.predict_device(b)
        float(jnp.sum(out))   # one sync for the chained pass
        best = max(best, n * batch / (time.perf_counter() - t0))
    return {"metric": "alexnet_infer_images_per_sec_per_chip",
            "value": round(best, 2), "unit": "images/sec/chip",
            "vs_baseline": None}


def bench_alexnet_latency_b1():
    """Serving latency: single-image (batch=1) forward, milliseconds per
    call including the host round trip — the number a latency-sensitive
    deployment of the exported artifact sees (throughput rows measure the
    opposite regime). Median of 50 calls after warmup."""
    import jax
    from cxxnet_tpu.models import alexnet_trainer
    from cxxnet_tpu.io.data import DataBatch
    tr = alexnet_trainer(batch_size=1, input_hw=227, dev="tpu",
                         extra_cfg=BF16)
    rs = np.random.RandomState(0)
    b = DataBatch()
    b.data = jax.device_put(rs.rand(1, 3, 227, 227).astype(np.float32))
    b.label = jax.device_put(np.zeros((1, 1), np.float32))
    b.batch_size = 1
    for _ in range(5):
        tr.predict(b)
    times = []
    for _ in range(50):
        t0 = time.perf_counter()
        tr.predict(b)   # device_get inside forces the sync
        times.append(time.perf_counter() - t0)
    med_ms = sorted(times)[len(times) // 2] * 1e3
    return {"metric": "alexnet_infer_latency_batch1",
            "value": round(med_ms, 3), "unit": "ms",
            "vs_baseline": None}


def _lm_decode(metric, batch, L, plen, extra=""):
    """Serving decode throughput: KV-cached greedy generation
    (Trainer.generate) — tokens/sec across `batch` streams from `plen`
    to the full context. Judged against the analytic HBM-bandwidth bound
    (`tools/roofline.py --decode`), not MFU."""
    from cxxnet_tpu.models import transformer_lm_trainer
    tr = transformer_lm_trainer(vocab=8192, seq=L, batch_size=batch,
                                dim=512, nhead=8, nlayer=4, dev="tpu",
                                extra_cfg=BF16 + extra)
    rs = np.random.RandomState(0)
    prompts = rs.randint(0, 8192, (batch, plen))
    n_new = L - plen
    tr.generate(prompts, n_new)   # compile + warm
    t0 = time.perf_counter()
    tr.generate(prompts, n_new)
    dt = time.perf_counter() - t0
    return {"metric": metric,
            "value": round(batch * n_new / dt, 2), "unit": "tokens/sec",
            "vs_baseline": None}


def bench_lm_decode():
    return _lm_decode("lm_decode_tokens_per_sec_per_chip", 8, 2048, 64)


def bench_lm_decode_b1():
    """Interactive single-stream decode: the latency-bound serving case."""
    return _lm_decode("lm_decode_b1_tokens_per_sec_per_chip", 1, 2048, 64)


def bench_lm_decode_long():
    """Long-context GQA + sliding-window serving: the window caps the KV
    read so the bound stays flat past L=1024."""
    return _lm_decode(
        "lm_decode_L8192_tokens_per_sec_per_chip", 8, 8192, 64,
        extra="nkvhead = 2\nattn_window = 1024\nrope = 1\n")


def bench_lm_decode_chunked():
    """The flash-decode while-loop (decode_chunk): reads only the live
    cache prefix per step instead of the full static length — the dense
    path's known ~2x read overhead (doc/performance.md decode roofline).
    Token-exactness is pinned in tests/test_decode.py; this row decides
    whether the while-loop overhead beats the saved bandwidth on-chip."""
    return _lm_decode("lm_decode_chunked_tokens_per_sec_per_chip",
                      8, 2048, 64, extra="decode_chunk = 256\n")


def bench_lm_decode_long_chunked():
    """Chunked decode under the long-context recipe: with a 1024 window
    the loop reads at most 5 x 256-row chunks per step regardless of
    position, vs the dense path's masked 8192-row read."""
    return _lm_decode(
        "lm_decode_L8192_chunked_tokens_per_sec_per_chip", 8, 8192, 64,
        extra="nkvhead = 2\nattn_window = 1024\nrope = 1\n"
              "decode_chunk = 256\n")


def bench_lm_decode_b1_chunked():
    """Interactive single-stream decode with flash-decode: batch 1 is
    where the dense full-cache read is the largest share of bytes/token
    (decode roofline: b1 sits at 42% of bound with the dense read)."""
    return _lm_decode("lm_decode_b1_chunked_tokens_per_sec_per_chip",
                      1, 2048, 64, extra="decode_chunk = 256\n")


def bench_serve_load():
    """Serve-under-load: concurrent clients against the servd frontend
    (utils/servd.py) on loopback — end-to-end per-request p50/p99
    latency (socket + admission queue + KV-cached decode) and shed rate,
    so tools/bench_compare.py gates serving-latency regressions
    (unit ms = direction-aware, higher is worse) the way it already
    gates throughput. One prompt-length signature: the decode program
    compiles once and every request rides the cached fast path."""
    import socket
    import threading
    from cxxnet_tpu.models import transformer_lm_trainer
    from cxxnet_tpu.utils import servd
    from cxxnet_tpu.utils.telemetry import percentile
    vocab, L, plen, n_new = 8192, 256, 32, 16
    tr = transformer_lm_trainer(vocab=vocab, seq=L, batch_size=8,
                                dim=256, nhead=4, nlayer=2, dev="tpu",
                                extra_cfg=BF16)

    def backend(toks, seq):
        return tr.generate(np.asarray([toks]), n_new)[0]

    rs = np.random.RandomState(0)
    prompt = rs.randint(0, vocab, plen).tolist()
    backend(prompt, 0)              # compile the (1, plen) decode once
    fe = servd.ServeFrontend(backend, queue_size=64)
    fe.start()
    port = fe.listen(0)
    nclients, per = 4, 8
    line = " ".join(map(str, prompt))
    lats, nshed, nerr, nsent = [], [0], [0], [0]
    lock = threading.Lock()

    def client():
        with socket.create_connection(("127.0.0.1", port),
                                      timeout=300) as c:
            f = c.makefile("r")
            for _ in range(per):
                t0 = time.perf_counter()
                c.sendall((line + "\n").encode())
                resp = f.readline()
                dt = time.perf_counter() - t0
                with lock:
                    nsent[0] += 1
                    if not resp:
                        # connection torn down: an error, NOT a ~0ms
                        # latency sample that would deflate the gated
                        # p50/p99 of a degraded run
                        nerr[0] += 1
                    elif resp.startswith("ERR busy"):
                        nshed[0] += 1       # shed = admission rejection
                    elif resp.startswith("ERR"):
                        nerr[0] += 1        # backend/deadline: not shed
                    else:
                        lats.append(dt)
                if not resp:
                    break

    threads = [threading.Thread(target=client) for _ in range(nclients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    fe.drain()
    lats.sort()
    # server-side phase attribution from the flight recorder: TTFT
    # (accept -> first token, the trainer's prefill/decode split) and
    # queue wait — the sub-fields the batching PR's before/after is
    # graded on (bench_compare gates them via "<metric>.<field>" keys)
    recs = [r for r in fe.flight.list() if r["outcome"] == "served"]
    ttfts = sorted(r["ttft_s"] for r in recs
                   if r.get("ttft_s") is not None)
    qwaits = sorted(r["phases"]["queue_wait"] for r in recs)
    # rates over requests actually ISSUED: a client whose connection died
    # stops early, and its unsent requests must not pad the denominator
    # (a fully degraded run would otherwise understate its error rate)
    total = max(1, nsent[0])
    return {"metric": "serve_loopback_p99_latency_ms",
            "value": round(1e3 * percentile(lats, 99), 3) if lats
            else None,
            "unit": "ms", "vs_baseline": None,
            "p50_ms": round(1e3 * percentile(lats, 50), 3) if lats
            else None,
            "ttft_p99_ms": round(1e3 * percentile(ttfts, 99), 3)
            if ttfts else None,
            "queue_wait_p99_ms": round(1e3 * percentile(qwaits, 99), 3)
            if qwaits else None,
            "shed_rate": round(nshed[0] / float(total), 4),
            "error_rate": round(nerr[0] / float(total), 4),
            "requests": nsent[0]}


class _PagedSlotBackend:
    """The serve benches' slot backend over the PAGED decode KV cache
    (doc/performance.md "Decode KV cache") — the same pool/session/
    admission-gate hook surface learn_task's production adapter
    exposes, minus the task indirection: sessions share one
    ``Trainer.decode_kv_pool``, admission is block-budgeted through
    ``kv_fresh_blocks``/``kv_free_blocks``, and ``kv_pool_account``
    feeds the /batchz + prefix_hit_rate sub-fields. The dispatcher's
    seq ordinal doubles as the sampling seed (greedy in the benches,
    so it only names the stream)."""

    def __init__(self, tr, buckets, n_new, block, pool_tokens,
                 prefix_reuse=True, retained_frac=1.0):
        self.tr = tr
        self.buckets = list(buckets)
        self.n_new = int(n_new)
        self.block = int(block)
        self.pool_tokens = int(pool_tokens)
        self.prefix_reuse = bool(prefix_reuse)
        self.retained_frac = float(retained_frac)

    def _pool(self):
        return self.tr.decode_kv_pool(self.block,
                                      pool_tokens=self.pool_tokens,
                                      prefix_reuse=self.prefix_reuse,
                                      retained_frac=self.retained_frac)

    def _live_pool(self):
        p = getattr(self.tr, "_kv_pool", None)
        return None if p is None or p.closed else p

    def session(self, nslots):
        return self.tr.decode_session(nslots, self.n_new,
                                      kv_pool=self._pool())

    def kv_pool_account(self):
        p = self._live_pool()
        return p.account() if p is not None else None

    def kv_free_blocks(self):
        # free + evictable-retained: under retention the gather budget
        # must see parked blocks as headroom (evict-before-defer)
        p = self._live_pool()
        return p.alloc.available_blocks if p is not None else None

    def kv_shed_retained(self, target_free):
        p = self._live_pool()
        if p is None:
            return 0
        return p.alloc.evict_retained(target_free=target_free)

    def kv_fresh_blocks(self, toks):
        p = self._live_pool()
        if p is None:
            return None
        return p.alloc.fresh_need(len(toks), self.n_new, toks)


def bench_serve_throughput():
    """Continuous-batching serving throughput: a closed-loop N-client
    flood through the BATCHING frontend (utils/servd.py slot_backend
    path over Trainer.decode_session) — the requests/sec/chip lever the
    batching arc is graded on, next to serve_loopback_p99_latency_ms.
    Headline value is rps (HIGHER is better — bench_compare keys the
    direction off the non-ms unit and the *_rps name); sub-fields carry
    the latency tail (p50/p99), the measured mean batch occupancy
    (sequences per decode pass — the coalescing proof), and the
    roofline decode-step bound (tokens/s) from the performance ledger:
    the ceiling the measured tokens/s reports against.

    The flood runs over the PAGED KV cache (serve_kv_block semantics;
    doc/performance.md "Decode KV cache"): ``kv_live_pct`` is the
    before/after headline — the dense PR 13 baseline read ~14% (every
    slot owned an l_max row); paged, waste is bounded by block
    granularity, so the mean should sit near 100 x live_rows /
    (blocks_held x block). ``prefix_hit_rate`` (identical prompts
    here, so it climbs fast after the first admission) and the
    exhaustion-defer count ride along, null-safe on a dense run."""
    import socket
    import threading
    from cxxnet_tpu.models import transformer_lm_trainer
    from cxxnet_tpu.utils import perf, servd, telemetry
    from cxxnet_tpu.utils.telemetry import percentile
    vocab, L, plen, n_new = 8192, 256, 32, 16
    bucket = 4
    tr = transformer_lm_trainer(vocab=vocab, seq=L, batch_size=8,
                                dim=256, nhead=4, nlayer=2, dev="tpu",
                                extra_cfg=BF16)

    backend = _PagedSlotBackend(tr, [bucket], n_new, block=16,
                                pool_tokens=bucket * L)
    fe = servd.ServeFrontend(None, slot_backend=backend,
                             queue_size=64, batch_max=bucket,
                             batch_window_ms=5.0,
                             # size the iteration ring for the WHOLE
                             # flood: at degraded occupancy the run is
                             # up to ~(nclients*per+1)*n_new
                             # iterations, and a silently truncated
                             # window would bias kv_live_pct /
                             # queue_age_p99_ms newest-ward exactly
                             # when the bench should catch a regression
                             batch_flight_cap=4096)
    fe.start()
    port = fe.listen(0)
    rs = np.random.RandomState(0)
    prompt = rs.randint(0, vocab, plen).tolist()
    line = " ".join(map(str, prompt))
    # warm the bucket: compiles (prefill + step + admit) happen here,
    # not inside the measured window
    from cxxnet_tpu.utils.servd import _ask
    _ask(port, line, timeout=600.0)
    occ0 = (fe._occ_iters, fe._occ_slots)
    iter0 = fe._iter_ord
    # bracket the flood for the autopsy/books sub-fields: records
    # before this mark are warm-up (whose verdicts MAY carry
    # compile_stall), and the auditor's violation count is deltaed so
    # other rows in this process cannot leak into this one
    nrec0 = len(fe.flight.list())
    telemetry.audit_sweep()
    books0 = telemetry.auditor().snapshot()["violations"]
    nclients, per = 6, 6
    lats, nerr, nsent = [], [0], [0]
    lock = threading.Lock()

    def client():
        with socket.create_connection(("127.0.0.1", port),
                                      timeout=600) as c:
            f = c.makefile("r")
            for _ in range(per):
                t0 = time.perf_counter()
                c.sendall((line + "\n").encode())
                resp = f.readline()
                dt = time.perf_counter() - t0
                with lock:
                    nsent[0] += 1
                    if not resp or resp.startswith("ERR"):
                        nerr[0] += 1
                    else:
                        lats.append(dt)
                if not resp:
                    break

    threads = [threading.Thread(target=client) for _ in range(nclients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    d_iters = fe._occ_iters - occ0[0]
    d_slots = fe._occ_slots - occ0[1]
    # the decode-datapath observability sub-fields (null-safe): mean
    # live-KV utilization and queue-age p99 over the flood window's
    # iteration records — kv_live_pct is THE paged-KV before/after
    # baseline (ROADMAP item 2: the reclaimable padding+dead-slot
    # share), queue_age_p99_ms the admission-pressure tail
    win = [r for r in fe.batch_flight.list() if r["iter"] > iter0]
    kv_pcts = [r["kv_live_pct"] for r in win
               if r.get("kv_live_pct") is not None]
    qages = sorted(r["queue_age_s"] for r in win
                   if r.get("queue_age_s") is not None)
    # the paged-pool account (null-safe: None end to end on a dense
    # backend) — prefix_hit_rate is token-weighted, recomputed by the
    # snapshot from the allocator's lifetime tallies
    snap = fe.batch_snapshot() or {}
    pool = snap.get("pool") or {}
    # the autopsy plane over the flood window: every flood request's
    # stamped verdict (warm bucket -> compile_stall share exactly 0),
    # plus the conservation-law auditor's verdict — swept BEFORE
    # drain, while this frontend's laws are still registered
    telemetry.audit_sweep()
    books1 = telemetry.auditor().snapshot()["violations"]
    allrec = fe.flight.list()                # newest first
    floodrec = allrec[:max(0, len(allrec) - nrec0)]
    verdicts = {}
    stall_s = wall_s = 0.0
    for rec in floodrec:
        aut = rec.get("autopsy")
        if not aut:
            continue
        verdicts[aut["primary"]] = verdicts.get(aut["primary"], 0) + 1
        stall_s += float((aut.get("causes") or {})
                         .get("compile_stall", 0.0))
        wall_s += float(aut.get("wall_s") or 0.0)
    fe.drain()
    lats.sort()
    total = max(1, nsent[0])
    return {"metric": "serve_throughput_rps",
            "value": round(len(lats) / wall, 3) if lats and wall > 0
            else None,
            "unit": "req/s", "vs_baseline": None,
            "p50_ms": round(1e3 * percentile(lats, 50), 3) if lats
            else None,
            "p99_ms": round(1e3 * percentile(lats, 99), 3) if lats
            else None,
            "mean_batch_occupancy": round(d_slots / float(d_iters), 3)
            if d_iters else None,
            "decode_bound_tokens_per_s":
            perf.decode_bound_tokens_per_s(n_new),
            "kv_live_pct": round(sum(kv_pcts) / len(kv_pcts), 2)
            if kv_pcts else None,
            "prefix_hit_rate": pool.get("prefix_hit_rate"),
            "kv_blocks_total": pool.get("blocks_total"),
            "kv_defers": pool.get("alloc_failures"),
            "queue_age_p99_ms": round(1e3 * percentile(qages, 99), 3)
            if qages else None,
            "error_rate": round(nerr[0] / float(total), 4),
            # the self-explaining-telemetry sub-fields: the flood's
            # primary-verdict histogram, the compile-stall share of
            # its wall time (0.0 on this warm bucket — any rise means
            # the flood paid a cliff), and the auditor's violation
            # delta across the row (0 on a healthy run; gated by
            # bench_compare as worse-when-higher)
            "autopsy_verdicts": verdicts or None,
            "autopsy_compile_stall_pct":
            round(100.0 * stall_s / wall_s, 3) if wall_s > 0 else None,
            "books_violations": books1 - books0,
            "requests": nsent[0], "bucket": bucket}


def bench_serve_prefix_reuse():
    """Shared-system-prompt serving flood over the paged KV cache: N
    closed-loop clients send prompts that share one long system
    prefix (full blocks) and differ only in a short user tail — the
    chatbot/agent fleet shape. The shared blocks prefill ONCE
    (refcounted in the pool's prefix trie); every later admission
    gathers them and computes only its tail, so the prefill phase
    shrinks by the hit rate. Headline is rps (HIGHER better);
    ``prefix_hit_rate`` should approach 100 x shared/plen once the
    flood is warm, and ``ttft_p99_ms`` carries the time-to-first-token
    win the reuse buys. CPU-measurable (tiny model, greedy), null-safe
    (a dense backend would simply report null prefix fields)."""
    import socket
    import threading
    from cxxnet_tpu.models import transformer_lm_trainer
    from cxxnet_tpu.utils import servd
    from cxxnet_tpu.utils.telemetry import percentile
    vocab, L, n_new = 8192, 256, 8
    block, shared, tail = 16, 48, 8        # plen 56: 3 shared blocks
    bucket = 4
    tr = transformer_lm_trainer(vocab=vocab, seq=L, batch_size=8,
                                dim=256, nhead=4, nlayer=2, dev="tpu",
                                extra_cfg=BF16)
    backend = _PagedSlotBackend(tr, [bucket], n_new, block=block,
                                pool_tokens=bucket * L)
    fe = servd.ServeFrontend(None, slot_backend=backend,
                             queue_size=64, batch_max=bucket,
                             batch_window_ms=5.0,
                             batch_flight_cap=4096)
    fe.start()
    port = fe.listen(0)
    rs = np.random.RandomState(7)
    system = rs.randint(0, vocab, shared).tolist()

    def prompt_line(i):
        # one shared system prefix, a per-request user tail: request i
        # reuses blocks request 0 loaded (the prefill-once contract)
        tl = ((np.arange(tail) * 31 + i * 7) % vocab).tolist()
        return " ".join(map(str, system + tl))

    # warm: the first admission prefills the WHOLE prompt and compiles
    # the (plen, 0) program; the second compiles the (plen, shared)
    # suffix program — both outside the measured window
    from cxxnet_tpu.utils.servd import _ask
    _ask(port, prompt_line(10001), timeout=600.0)
    _ask(port, prompt_line(10002), timeout=600.0)
    nclients, per = 6, 4
    lats, ttfts, nerr, nsent = [], [], [0], [0]
    lock = threading.Lock()

    def client(ci):
        with socket.create_connection(("127.0.0.1", port),
                                      timeout=600) as c:
            f = c.makefile("r")
            for j in range(per):
                t0 = time.perf_counter()
                c.sendall((prompt_line(ci * per + j) + "\n").encode())
                resp = f.readline()
                dt = time.perf_counter() - t0
                with lock:
                    nsent[0] += 1
                    if not resp or resp.startswith("ERR"):
                        nerr[0] += 1
                    else:
                        lats.append(dt)
                if not resp:
                    break

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(nclients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    # TTFT from the request flight ring: the prefill phase is where
    # prefix reuse pays (only the tail is computed)
    ttfts = [1e3 * r["ttft_s"] for r in fe.flight.list()
             if r.get("ttft_s") is not None]
    snap = fe.batch_snapshot() or {}
    pool = snap.get("pool") or {}
    fe.drain()
    lats.sort()
    total = max(1, nsent[0])
    return {"metric": "serve_prefix_reuse_rps",
            "value": round(len(lats) / wall, 3) if lats and wall > 0
            else None,
            "unit": "req/s", "vs_baseline": None,
            "p50_ms": round(1e3 * percentile(lats, 50), 3) if lats
            else None,
            "p99_ms": round(1e3 * percentile(lats, 99), 3) if lats
            else None,
            "ttft_p99_ms": round(percentile(sorted(ttfts), 99), 3)
            if ttfts else None,
            "prefix_hit_rate": pool.get("prefix_hit_rate"),
            "prefix_hits": pool.get("prefix_hits"),
            "cow_copies": pool.get("cow_copies"),
            "kv_live_pct": snap.get("kv_live_pct"),
            "kv_defers": pool.get("alloc_failures"),
            "error_rate": round(nerr[0] / float(total), 4),
            "requests": nsent[0], "bucket": bucket,
            "shared_tokens": shared, "prompt_tokens": shared + tail}


def bench_serve_multiturn_ttft():
    """Multi-turn conversation TTFT over the RETAINED conversation
    cache (doc/robustness.md "Memory governance"): turn N+1 extends
    turn N's prompt, so with retention the retired chain REVIVES at
    admission (refcount 0 -> 1) and prefill computes only the new
    tail; with a cold trie (serve_retained_frac 0 — the PR 15
    free-instantly contract) every turn re-prefills the whole
    conversation. Headline is the warm-trie turn-N+1 TTFT in ms
    (LOWER is better — the *_ms direction rule); ``cold_ttft_ms``
    carries the same turn over the cold trie, measured with identical
    programs (both program shapes warmed outside the window), so
    warm < cold is pure recompute avoided, not compile skew.
    ``prefix_hit_rate``/``kv_retained_pct`` pin that the warm pass
    really served from retained mass. CPU-measurable (tiny model,
    greedy, sequential turns)."""
    from cxxnet_tpu.models import transformer_lm_trainer
    from cxxnet_tpu.utils import servd
    from cxxnet_tpu.utils.servd import _ask
    from cxxnet_tpu.utils.telemetry import percentile
    vocab, L, n_new = 8192, 256, 8
    block, bucket = 16, 2
    # a LONG turn 1 (most of the context window) and a short turn-2
    # tail: the shape where retention pays — turn 2 revives 192 tokens
    # and computes 16, vs a 208-token cold re-prefill
    base, grow, nconv = 192, 16, 4
    tr = transformer_lm_trainer(vocab=vocab, seq=L, batch_size=8,
                                dim=256, nhead=4, nlayer=2, dev="tpu",
                                extra_cfg=BF16)
    rs = np.random.RandomState(11)
    # conversations: distinct content, identical shape — turn 2's
    # prompt is turn 1's plus one grown block
    convs = [rs.randint(0, vocab, base + grow).tolist()
             for _ in range(nconv + 1)]

    def run_pass(retained_frac):
        backend = _PagedSlotBackend(tr, [bucket], n_new, block=block,
                                    pool_tokens=bucket * L,
                                    retained_frac=retained_frac)
        fe = servd.ServeFrontend(None, slot_backend=backend,
                                 queue_size=64, batch_max=bucket,
                                 batch_window_ms=5.0,
                                 batch_flight_cap=4096)
        fe.start()
        port = fe.listen(0)
        # warm BOTH program shapes outside the window: the cold pass
        # prefills (base+grow, 0), the warm pass (base, 0) then the
        # revived suffix (base+grow, base) — conversation 0 is the
        # sacrificial compile turn in each pass
        toks = convs[0]
        _ask(port, " ".join(map(str, toks[:base])), timeout=600.0)
        _ask(port, " ".join(map(str, toks)), timeout=600.0)
        for conv in convs[1:]:
            _ask(port, " ".join(map(str, conv[:base])), timeout=600.0)
            _ask(port, " ".join(map(str, conv)), timeout=600.0)
        # turn-N+1 TTFT from the flight ring, keyed by prompt length
        # (only final turns are base+grow tokens long); the ring is
        # newest-first, so the sacrificial compile turn is LAST
        ttfts = [1e3 * r["ttft_s"] for r in fe.flight.list()
                 if r.get("ttft_s") is not None
                 and r.get("tokens_in") == base + grow][:-1]
        snap = fe.batch_snapshot() or {}
        pool = snap.get("pool") or {}
        fe.drain()
        tr.release_kv_pool()
        return ttfts, pool

    cold_ttfts, _ = run_pass(0.0)
    warm_ttfts, pool = run_pass(1.0)
    warm = (round(percentile(sorted(warm_ttfts), 50), 3)
            if warm_ttfts else None)
    cold = (round(percentile(sorted(cold_ttfts), 50), 3)
            if cold_ttfts else None)
    return {"metric": "serve_multiturn_ttft", "value": warm,
            "unit": "ms", "vs_baseline": None,
            "cold_ttft_ms": cold,
            "ttft_speedup": round(cold / warm, 3)
            if warm and cold else None,
            "prefix_hit_rate": pool.get("prefix_hit_rate"),
            "retained_hit_rate": pool.get("retained_hit_rate"),
            "kv_retained_pct": pool.get("kv_retained_pct"),
            "retained_hits": pool.get("retained_hits"),
            "retained_evictions": pool.get("retained_evictions"),
            "conversations": nconv, "turn_tokens": base + grow,
            "revived_tokens": base}


def bench_serve_fleet():
    """Fleet-under-load: the same loopback flood as
    serve_loopback_p99_latency_ms, but through the replicated-fleet
    router (utils/routerd.py) over TWO local servd replicas — the
    serving topology doc/serving.md "Replicated serving fleet" ships.
    End-to-end p50/p99 through router+replica, plus the fleet-health
    sub-fields the chaos arc is graded on: shed_rate (admission sheds
    that survived retry) and retry_rate (retries per issued request).
    The two replicas share one chip (and one decode program, behind a
    lock — replica concurrency buys admission/failover, not parallel
    decode on a single chip), so the row measures ROUTER overhead and
    fleet correctness, not extra throughput; gated direction-aware by
    bench_compare (ms unit, *_rate sub-fields) next to the
    single-replica row."""
    import socket
    import threading
    from cxxnet_tpu.models import transformer_lm_trainer
    from cxxnet_tpu.utils import routerd, servd, statusd
    from cxxnet_tpu.utils.telemetry import percentile
    vocab, L, plen, n_new = 8192, 256, 32, 16
    tr = transformer_lm_trainer(vocab=vocab, seq=L, batch_size=8,
                                dim=256, nhead=4, nlayer=2, dev="tpu",
                                extra_cfg=BF16)
    # ONE compiled decode program serves both replicas: generate() is
    # not reentrant, so the backend serializes on a lock (the fleet's
    # win is availability; a single chip has no parallel decode to give)
    gen_lock = threading.Lock()

    def backend(toks, seq):
        with gen_lock:
            return tr.generate(np.asarray([toks]), n_new)[0]

    rs = np.random.RandomState(0)
    prompt = rs.randint(0, vocab, plen).tolist()
    backend(prompt, 0)              # compile the (1, plen) decode once
    replicas, status = [], []
    for _ in range(2):
        fe = servd.ServeFrontend(backend, queue_size=64)
        fe.start()
        fe.listen(0)
        ss = statusd.StatusServer(0, host="127.0.0.1").start()
        ss.register_probe("serving", fe.health_probe)
        replicas.append(fe)
        status.append(ss)
    router = routerd.Router(
        [("127.0.0.1", fe.port, ss.port)
         for fe, ss in zip(replicas, status)],
        probe_ms=100.0, retries=2)
    router.start()
    rport = router.listen(0)
    nclients, per = 4, 8
    line = " ".join(map(str, prompt))
    lats, nshed, nerr, nsent = [], [0], [0], [0]
    lock = threading.Lock()

    def client():
        with socket.create_connection(("127.0.0.1", rport),
                                      timeout=300) as c:
            f = c.makefile("r")
            for _ in range(per):
                t0 = time.perf_counter()
                c.sendall((line + "\n").encode())
                resp = f.readline()
                dt = time.perf_counter() - t0
                with lock:
                    nsent[0] += 1
                    if not resp:
                        nerr[0] += 1        # torn connection != 0ms
                    elif resp.startswith("ERR busy"):
                        nshed[0] += 1       # shed survived the retries
                    elif resp.startswith("ERR"):
                        nerr[0] += 1
                    else:
                        lats.append(dt)
                if not resp:
                    break

    threads = [threading.Thread(target=client) for _ in range(nclients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    rstats = router.drain()
    for fe in replicas:
        fe.drain()
    for ss in status:
        ss.stop()
    # fleet TTFT through the trace join: the router minted ONE id per
    # request and stamped it on every forward, so the replica flight
    # record that carries the honest device-level ttft_s is found by
    # id — the same join `telemetry_report.py --fleet` does offline
    routed = {rec["id"] for rec in router.flight.list()
              if rec.get("outcome") == "served"}
    ttfts = sorted(rec["ttft_s"] for fe in replicas
                   for rec in fe.flight.list()
                   if rec.get("id") in routed
                   and rec.get("ttft_s") is not None)
    lats.sort()
    total = max(1, nsent[0])
    return {"metric": "serve_fleet_p99_latency_ms",
            "value": round(1e3 * percentile(lats, 99), 3) if lats
            else None,
            "unit": "ms", "vs_baseline": None,
            "p50_ms": round(1e3 * percentile(lats, 50), 3) if lats
            else None,
            "ttft_p99_ms": round(1e3 * percentile(ttfts, 99), 3)
            if ttfts else None,
            "shed_rate": round(nshed[0] / float(total), 4),
            "retry_rate": round(rstats.get("retries", 0)
                                / float(total), 4),
            "error_rate": round(nerr[0] / float(total), 4),
            "replicas": len(replicas),
            "requests": nsent[0]}


def bench_serve_tenant_isolation():
    """Multi-tenant QoS under a noisy-tenant flood, through the
    replicated fleet: 2 active replicas + 1 standby behind the router,
    tenants ``noisy:1,victim:4`` fleet-wide, per-tenant SLO windows
    federating. A closed-loop noisy flood saturates the fleet while a
    light victim workload runs beside it — the row measures the three
    isolation guarantees ISSUE 13's chaos arc is graded on: the
    victim's p99 (headline, ms — holds while the flood sheds), the
    noisy tenant's shed rate (HIGHER is the fairness actually engaging
    — bench_compare knows this direction), and the autoscaler's
    scale-up ADMISSION latency (flood start -> standby admitted into
    rotation, driven live by the router's prober loop). Admission is a
    stub-side measure: the standby here serves the same warm backend,
    so "admitted" == "useful". Against a cold real replica it is NOT —
    the admitted standby still owes its compile grid; the honest
    admitted->useful gap is what ``serve_scale_up_to_first_token_s``
    (the cold-start row) measures. Null-safe like every serve row."""
    import threading
    from cxxnet_tpu.models import transformer_lm_trainer
    from cxxnet_tpu.utils import routerd, servd, statusd
    from cxxnet_tpu.utils.telemetry import percentile
    from tests import faultinject
    vocab, L, plen, n_new = 8192, 256, 32, 8
    tenants = "noisy:1,victim:4"
    tr = transformer_lm_trainer(vocab=vocab, seq=L, batch_size=8,
                                dim=256, nhead=4, nlayer=2, dev="tpu",
                                extra_cfg=BF16)
    gen_lock = threading.Lock()

    def backend(toks, seq):
        with gen_lock:
            return tr.generate(np.asarray([toks]), n_new)[0]

    rs = np.random.RandomState(0)
    prompt = rs.randint(0, vocab, plen).tolist()
    backend(prompt, 0)              # compile the (1, plen) decode once
    line = " ".join(map(str, prompt))

    def replica():
        slo_t = {t: statusd.SLOTracker(availability=0.99,
                                       min_requests=4, min_bad=3,
                                       window_s=60.0)
                 for t in ("noisy", "victim")}
        fe = servd.ServeFrontend(backend, queue_size=8,
                                 tenants=tenants,
                                 tenant_default="victim",
                                 slo_tenants=slo_t,
                                 slo=statusd.SLOTracker(
                                     availability=0.99, min_requests=8,
                                     min_bad=3, window_s=60.0))
        fe.start()
        fe.listen(0)
        ss = statusd.StatusServer(0, host="127.0.0.1").start()
        ss.register_probe("serving", fe.health_probe)
        ss.slo = fe.slo
        ss.slo_tenants = slo_t
        ss.flight = fe.flight
        return fe, ss

    actives = [replica() for _ in range(2)]
    standby = replica()
    router = routerd.Router(
        [("127.0.0.1", fe.port, ss.port) for fe, ss in actives],
        probe_ms=100.0, retries=2, federate_ms=200.0,
        standby_replicas=[("127.0.0.1", standby[0].port,
                           standby[1].port)],
        scale_up_burn=1.0, scale_down_idle_s=3600.0,
        scale_cooldown_s=0.5, tenants=tenants,
        tenant_default="victim")
    router.start()
    rport = router.listen(0)
    router.probe_now()
    flood_s = 4.0
    results = {}
    t0 = time.perf_counter()

    def flood(name, **kw):
        results[name] = faultinject.tenant_flood(rport, name,
                                                 duration_s=flood_s,
                                                 toks=line, **kw)

    ths = [threading.Thread(target=flood, args=("noisy",),
                            kwargs={"nclients": 6}),
           threading.Thread(target=flood, args=("victim",),
                            kwargs={"nclients": 2})]
    for t in ths:
        t.start()
    # the autoscaler runs live on the prober cadence: poll for its
    # scale-up while the flood is on — flood start -> standby admitted
    scale_latency = None
    while time.perf_counter() - t0 < flood_s:
        if router.scale_snapshot()["events"] > 0:
            scale_latency = time.perf_counter() - t0
            break
        time.sleep(0.05)
    for t in ths:
        t.join()
    router.drain()
    for fe, ss in actives + [standby]:
        fe.drain(timeout_ms=2000)
        ss.stop()
    noisy, victim = results.get("noisy"), results.get("victim")
    vlats = sorted(victim["latencies"]) if victim else []
    nlats = sorted(noisy["latencies"]) if noisy else []

    def rate(d, key):
        return round(d[key] / float(d["sent"]), 4) \
            if d and d["sent"] else None

    return {"metric": "serve_tenant_isolation",
            "value": round(1e3 * percentile(vlats, 99), 3) if vlats
            else None,
            "unit": "ms", "vs_baseline": None,
            "victim_p99_ms": round(1e3 * percentile(vlats, 99), 3)
            if vlats else None,
            "victim_p50_ms": round(1e3 * percentile(vlats, 50), 3)
            if vlats else None,
            "victim_shed_rate": rate(victim, "shed"),
            "noisy_shed_rate": rate(noisy, "shed"),
            "noisy_p99_ms": round(1e3 * percentile(nlats, 99), 3)
            if nlats else None,
            "fleet_scale_admission_latency_s": round(scale_latency, 3)
            if scale_latency is not None else None,
            "lost": (victim["lost"] if victim else 0)
            + (noisy["lost"] if noisy else 0),
            "victim_requests": victim["sent"] if victim else 0,
            "noisy_requests": noisy["sent"] if noisy else 0}


def bench_serve_chaos_availability():
    """Availability through a SIGKILL: a 3-replica batched fleet
    (``servd --stub`` subprocesses — a kill must take a PROCESS, and
    the row grades the router's failover datapath, which is
    model-free: replay/hedge correctness against the real decode
    backend is tests/test_failover.py's job) floods through the
    router with deterministic replay on, one replica is SIGKILLed
    mid-flood with requests decoding aboard its batch, and the row
    reports the fraction of flood requests answered OK (headline,
    pct — bench_compare gates it worse-when-LOWER) plus the failover
    engagement sub-fields: error_rate, replays (a drop to zero means
    the failover path stopped firing), and the p99 of requests issued
    inside the kill window next to the overall p99. Null-safe like
    every serve row."""
    import threading
    from cxxnet_tpu.utils import routerd, telemetry
    from cxxnet_tpu.utils.telemetry import percentile
    from tests import faultinject
    fleet = faultinject.spawn_fleet(3, batch_max=4, n_new=8,
                                    per_token_ms=10)
    router = routerd.Router([r.spec for r in fleet], probe_ms=100.0,
                            retries=2, stall_s=2.0,
                            probe_backoff_cap_s=0.5)
    router.start()
    rport = router.listen(0)
    router.probe_now()
    # conservation-law bracket: the router's books must reconcile
    # through the SIGKILL (deltaed so other rows cannot leak in)
    telemetry.audit_sweep()
    books0 = telemetry.auditor().snapshot()["violations"]
    flood_s, kill_at, kill_win = 3.0, 0.8, 1.0
    lock = threading.Lock()
    samples = []                     # (t_issue_rel, latency_s, ok)
    t0 = time.perf_counter()
    stop_at = t0 + flood_s
    faultinject.kill9(fleet[0], delay_s=kill_at)

    def client(i):
        while time.perf_counter() < stop_at:
            t1 = time.perf_counter()
            try:
                resp = faultinject.serve_request(rport, "%d" % (10 + i),
                                                 timeout=10)
            except OSError:
                resp = None
            ok = bool(resp) and not resp.startswith("ERR")
            with lock:
                samples.append((t1 - t0, time.perf_counter() - t1, ok))

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # sweep while the router's laws are still registered: a kill that
    # corrupted the route books must show up HERE, not vanish at drain
    telemetry.audit_sweep()
    books1 = telemetry.auditor().snapshot()["violations"]
    rstats = router.drain()
    faultinject.stop_fleet(fleet)
    sent = len(samples)
    lats = sorted(dt for _, dt, ok in samples if ok)
    kill_lats = sorted(dt for ti, dt, ok in samples
                       if ok and kill_at <= ti < kill_at + kill_win)
    nok = len(lats)
    return {"metric": "serve_chaos_availability",
            "value": round(100.0 * nok / sent, 3) if sent else None,
            "unit": "pct", "vs_baseline": None,
            "error_rate": round((sent - nok) / float(sent), 4)
            if sent else None,
            "replays": rstats.get("replays", 0),
            "lost_contact": rstats.get("lost_contact", 0),
            "p99_ms": round(1e3 * percentile(lats, 99), 3) if lats
            else None,
            "kill_window_p99_ms": round(1e3 * percentile(kill_lats,
                                                         99), 3)
            if kill_lats else None,
            # the metrics auditor's verdict on the kill: route books
            # must reconcile through a SIGKILL (worse-when-higher)
            "books_violations": books1 - books0,
            "replicas": len(fleet), "requests": sent}


def bench_serve_hedged_tail():
    """What tail hedging buys: a 2-replica fleet with one deliberate
    straggler (``servd --stub`` subprocesses, one at ``--delay-ms
    200`` — stub-based for the same reason as the chaos row: the
    hedge race is router-layer, model-free), flooded twice with the
    SAME client schedule — hedging off, then ``route_hedge_ms = 40``.
    Headline: the hedged p99 (ms, worse-when-HIGHER as usual);
    ``p99_unhedged_ms`` rides along as the honest before, and
    ``hedges`` / ``hedge_wins`` gate worse-when-LOWER (zero means the
    hedge lane stopped engaging and the headline quietly became the
    unhedged tail). Null-safe like every serve row."""
    import threading
    from cxxnet_tpu.utils import routerd
    from cxxnet_tpu.utils.telemetry import percentile
    from tests import faultinject

    def flood(rport, n=40, nclients=4):
        lats, lock = [], threading.Lock()

        def client(k):
            for j in range(n // nclients):
                t1 = time.perf_counter()
                try:
                    resp = faultinject.serve_request(
                        rport, "%d" % (10 + k + j), timeout=10)
                except OSError:
                    resp = None
                if resp and not resp.startswith("ERR"):
                    with lock:
                        lats.append(time.perf_counter() - t1)

        ths = [threading.Thread(target=client, args=(k,))
               for k in range(nclients)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        return sorted(lats)

    out = {"unhedged": None, "hedged": None, "stats": {}}
    for mode, hedge_ms in (("unhedged", 0.0), ("hedged", 40.0)):
        a = faultinject._start_stub(delay_ms=200.0)
        b = faultinject._start_stub()
        procs = []
        for proc, args in (a, b):
            port, sp = faultinject._await_ports(proc)
            r = faultinject.FleetReplica(proc, port, sp, args)
            procs.append(r)
        router = routerd.Router([r.spec for r in procs],
                                probe_ms=100.0, retries=1,
                                hedge_ms=hedge_ms)
        router.start()
        rport = router.listen(0)
        router.probe_now()
        out[mode] = flood(rport)
        st = router.drain()
        if mode == "hedged":
            out["stats"] = st
        faultinject.stop_fleet(procs)
    hl, ul, st = out["hedged"], out["unhedged"], out["stats"]
    return {"metric": "serve_hedged_tail",
            "value": round(1e3 * percentile(hl, 99), 3) if hl
            else None,
            "unit": "ms", "vs_baseline": None,
            "p99_unhedged_ms": round(1e3 * percentile(ul, 99), 3)
            if ul else None,
            "p50_ms": round(1e3 * percentile(hl, 50), 3) if hl
            else None,
            "hedges": st.get("hedges", 0),
            "hedge_wins": st.get("hedge_wins", 0),
            "discarded_late": st.get("discarded_late", 0),
            "requests": len(hl) + len(ul)}


def bench_serve_cold_start():
    """HONEST cold-start / scale-up / reload latency against a REAL
    jax replica (doc/performance.md "Compile cliff") — three rows,
    measured in one run so they share the trainer:

    * ``serve_cold_start_to_ready_s``: trainer construction -> the
      full expected program grid warm (``ready_pct`` 100 after the
      warm-up sweep over ``plens``) — what a replica actually owes
      before it is USEFUL, not merely admitted.
    * ``serve_scale_up_to_first_token_s``: the first request against
      the cold replica -> its first token, server-side TTFT from the
      flight recorder, with the in-band compile stall attributed
      (``compile_stall_s``) — the admitted->useful gap the
      tenant-isolation row's ``fleet_scale_admission_latency_s``
      deliberately does NOT include.
    * ``serve_reload_capacity_dip``: a steady closed-loop flood with a
      rolling reload fired mid-flood (``reload_fn`` drops the jit
      cache, the real model-swap cost) — fractional completions/sec
      lost in the post-reload window vs the pre-reload window, stalls
      attributed on the post-reload requests (``reload_stall_s``).

    A PRIVATE perf ledger owns the warm account so programs warmed by
    earlier bench rows cannot pre-warm the grid (cold start must start
    at 0%% ready); the shared ledger's recompile hook is re-armed on
    the way out. Null-safe like every serve row."""
    from cxxnet_tpu.models import transformer_lm_trainer
    from cxxnet_tpu.utils import perf, servd
    from cxxnet_tpu.utils.servd import _ask
    vocab, L, n_new = 8192, 64, 4
    plens, bucket = [8, 16], 1
    shared_was_enabled = perf.enabled()
    lg = perf.Ledger().enable()
    fe = None
    t0 = time.perf_counter()
    try:
        tr = transformer_lm_trainer(vocab=vocab, seq=L, batch_size=4,
                                    dim=128, nhead=4, nlayer=2,
                                    dev="tpu", extra_cfg=BF16)
        lg.set_expected_grid(tr.expected_decode_grid([bucket], plens))

        class _Dense:
            # dense slot backend over the real decode datapath — the
            # minimal duck interface (buckets + session)
            buckets = [bucket]

            def session(self, nslots):
                return tr.decode_session(nslots, n_new)

        def reload_fn():
            # the real model-swap cost: the decode programs die with
            # the old params; the warm account resets with them so the
            # readiness series stays honest through the roll
            tr._clear_jit_cache()
            lg.reset()
            return True

        fe = servd.ServeFrontend(None, slot_backend=_Dense(),
                                 queue_size=32, batch_max=bucket,
                                 batch_window_ms=2.0,
                                 reload_fn=reload_fn)
        fe.start()
        fe.set_warm_account(lg.readiness, ready_pct=0.0)
        port = fe.listen(0)
        rs = np.random.RandomState(0)
        lines = [" ".join(map(str, rs.randint(0, vocab, p)))
                 for p in plens]
        # warm-up sweep: one request per declared prompt length — the
        # first pays prefill+admit+step compiles IN-BAND (scale-up to
        # first token), the rest fill out the prefill grid
        t_ready = None
        for ln in lines:
            _ask(port, ln, timeout=600.0)
            rd = lg.readiness()
            if t_ready is None and rd.get("ready_pct") == 100.0:
                t_ready = time.perf_counter() - t0
        served = [r for r in fe.flight.list()
                  if r["outcome"] == "served"]
        first = served[0] if served else {}
        rd = lg.readiness()
        # steady closed-loop flood (batch-1 capacity), rolling reload
        # fired mid-flood: the dip is completions/sec after vs before
        nflood, reload_at = 12, 6
        done_ts, t_r = [], None
        k0 = len(served)
        t_flood = time.perf_counter()
        for i in range(nflood):
            if i == reload_at:
                fe.request_reload()
                t_r = time.perf_counter()
            _ask(port, lines[0], timeout=600.0)
            done_ts.append(time.perf_counter())
        dip = None
        if t_r is not None and done_ts:
            w = min(t_r - t_flood, done_ts[-1] - t_r)
            pre = sum(1 for t in done_ts if t_r - w < t <= t_r)
            post = sum(1 for t in done_ts if t_r < t <= t_r + w)
            if pre:
                dip = round(max(0.0, 1.0 - post / float(pre)), 4)
        flood_recs = [r for r in fe.flight.list()
                      if r["outcome"] == "served"][k0 + reload_at:]
        stalls = [r.get("compile_stall_s") or 0.0 for r in flood_recs]
        rd_after = lg.readiness()
        return [
            {"metric": "serve_cold_start_to_ready_s",
             "value": round(t_ready, 3) if t_ready is not None
             else None,
             "unit": "s", "vs_baseline": None,
             "ready_programs_pct": rd.get("ready_pct"),
             "programs_expected": rd.get("expected"),
             "programs_warm": rd.get("warm")},
            {"metric": "serve_scale_up_to_first_token_s",
             "value": round(first["ttft_s"], 3)
             if first.get("ttft_s") is not None else None,
             "unit": "s", "vs_baseline": None,
             "compile_stall_s": first.get("compile_stall_s")},
            {"metric": "serve_reload_capacity_dip",
             "value": dip, "unit": "ratio", "vs_baseline": None,
             "reload_stall_s": round(max(stalls), 6) if stalls
             else None,
             "ready_programs_pct": rd_after.get("ready_pct"),
             "flood_requests": len(done_ts)},
        ]
    finally:
        if fe is not None:
            fe.drain(timeout_ms=2000)
        lg.disable()
        if shared_was_enabled:
            # give the recompile hook back to the shared ledger
            perf.enable()


def bench_mnist_mlp():
    tr = _conf_trainer(MNIST_MLP, (1, 1, 784), 100, extra=BF16)
    ips = _throughput(tr, (1, 1, 784), 10, 100, steps=100)
    return {"metric": "mnist_mlp_images_per_sec_per_chip",
            "value": round(ips, 2), "unit": "images/sec/chip",
            "vs_baseline": None}


def bench_mnist_conv():
    tr = _conf_trainer(MNIST_CONV, (1, 28, 28), 100, extra=BF16)
    ips = _throughput(tr, (1, 28, 28), 10, 100, steps=100)
    return {"metric": "mnist_conv_images_per_sec_per_chip",
            "value": round(ips, 2), "unit": "images/sec/chip",
            "vs_baseline": None}


def bench_bowl():
    tr = _conf_trainer(BOWL, (3, 40, 40), 64, extra=BF16)
    ips = _throughput(tr, (3, 40, 40), 121, 64, steps=60)
    # reference: ~5 min to convergence on a GTX 780 (no throughput number)
    return {"metric": "kaggle_bowl_images_per_sec_per_chip",
            "value": round(ips, 2), "unit": "images/sec/chip",
            "vs_baseline": None}


def _make_jpeg_corpus(dirname, n, hw=256, n_class=1000, quality=90):
    """Synthesize an ImageNet-shaped JPEG corpus + .lst (reference list
    format: index label filename)."""
    import cv2
    os.makedirs(dirname, exist_ok=True)
    rs = np.random.RandomState(0)
    lst_path = os.path.join(dirname, "bench.lst")
    # a few noise textures stamped with per-image shifts: realistic JPEG
    # entropy without n full random draws
    protos = [rs.randint(0, 255, (hw, hw, 3), np.uint8) for _ in range(8)]
    with open(lst_path, "w") as lst:
        for i in range(n):
            img = np.roll(protos[i % 8], i * 37 % hw, axis=1)
            fname = "b_%05d.jpg" % i
            cv2.imwrite(os.path.join(dirname, fname), img,
                        [cv2.IMWRITE_JPEG_QUALITY, quality])
            lst.write("%d %d %s\n" % (i, i % n_class, fname))
    return lst_path


def _pipeline_iterator(lst_path, bin_path, batch, decode_thread=None):
    from cxxnet_tpu.io import create_iterator
    from cxxnet_tpu.utils.config import parse_config_string
    cfg = """
iter = imgbinx
  image_list = "%s"
  image_bin = "%s"
  shuffle = 1
  rand_crop = 1
  rand_mirror = 1
  output_uint8 = 1
  batch_size = %d
  round_batch = 1
  input_shape = 3,227,227
  silent = 1
%s
iter = threadbuffer
  silent = 1
""" % (lst_path, bin_path, batch,
       "  decode_thread = %d" % decode_thread if decode_thread else "")
    pairs = [(k, v) for k, v in parse_config_string(cfg)]
    it = create_iterator(pairs)
    it.init()
    return it


def bench_alexnet_pipeline(io_only=False):
    """imgbinx -> augment -> threadbuffer -> trainer, real JPEG decode.
    io_only=True stops before the trainer: the host-side feed benchmark
    (no device, no tunnel) — `python bench.py io`."""
    import tempfile
    if not io_only:
        import jax
        import jax.numpy as jnp
        from cxxnet_tpu.models import alexnet_trainer

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tools"))
    from im2bin import im2bin

    batch = 256
    n_img = 2048
    out = []
    with tempfile.TemporaryDirectory() as td:
        lst = _make_jpeg_corpus(os.path.join(td, "imgs"), n_img)
        bin_path = os.path.join(td, "bench.bin")
        im2bin(lst, os.path.join(td, "imgs"), bin_path)

        # io-only rate (decode + augment + batch, no device work) at a
        # worker sweep: the host-feed scaling curve the VERDICT asked to
        # put against the measured device rate. On this 1-core sandbox
        # the sweep is flat by construction (off-GIL decode can't run in
        # parallel with one core); the per-worker rows are the recipe a
        # real host reruns to size decode_thread.
        ncore = os.cpu_count() or 1
        for nw in (1, 2, 4):
            it = _pipeline_iterator(lst, bin_path, batch, decode_thread=nw)
            for _ in it:  # warm-up epoch: page cache + decode-pool spin-up
                pass
            t0 = time.perf_counter()
            n = sum(b.batch_size - b.num_batch_padd for b in it)
            io_ips = n / (time.perf_counter() - t0)
            it.close()
            out.append({"metric":
                        "alexnet_pipeline_io_only_images_per_sec_w%d" % nw,
                        "value": round(io_ips, 2), "unit": "images/sec",
                        "vs_baseline": None, "host_cores": ncore})
        # feed margin vs the committed on-chip device rate (BENCH_r01:
        # 15047 img/s/chip): >1 means this host feeds the chip
        out.append({"metric": "alexnet_pipeline_feed_margin_vs_15047",
                    "value": round(io_ips / 15047.0, 4), "unit": "ratio",
                    "vs_baseline": None, "host_cores": ncore})
        if io_only:
            return out

        # pipeline-fed training: uint8 ships over H2D (4x less than f32),
        # normalization happens on device (input_divideby); fresh iterator
        # at the default decode_thread (independent of the sweep above)
        it = _pipeline_iterator(lst, bin_path, batch)
        tr = alexnet_trainer(batch_size=batch, input_hw=227, dev="tpu",
                             extra_cfg=BF16 + "input_divideby = 256\n")
        for b in it:        # warm-up epoch: jit compile + steady decode
            tr.update(b)
        t0 = time.perf_counter()
        n = 0
        t_input = 0.0       # host blocked on the loader (the starvation
                            # fraction the train loop also reports)
        for _ in range(2):  # two measured epochs
            ti = time.perf_counter()
            for b in it:
                t_input += time.perf_counter() - ti
                tr.update(b)
                n += b.batch_size - b.num_batch_padd
                ti = time.perf_counter()
        float(jnp.sum(next(v for p in tr.params for v in p.values())))
        wall = time.perf_counter() - t0
        ips = n / wall
        out.append({"metric": "alexnet_pipeline_fed_images_per_sec_per_chip",
                    "value": round(ips, 2), "unit": "images/sec/chip",
                    "vs_baseline": round(ips / 2000.0, 4),
                    "input_wait_frac": round(t_input / wall, 4)})
        # stop the decode pool + prefetch thread so later benches in the
        # same process don't contend for host cores
        it.close()
    return out


def _error_line(msg, extra=None):
    """The one-JSON-line contract, structured-failure form: the driver
    records a parseable line instead of a hang/timeout. ``extra``
    carries the analytic perf fields a CPU-side compile can still
    produce with the tunnel down."""
    row = {
        "metric": "alexnet_imagenet_images_per_sec_per_chip",
        "value": None, "unit": "images/sec/chip", "vs_baseline": None,
        "error": msg,
    }
    if extra:
        row.update(extra)
    return json.dumps(row)


def _analytic_fields(model="alexnet"):
    """The headline row's ANALYTIC perf fields, computed on CPU: lower
    the same train step the bench would have run, read XLA
    cost_analysis FLOPs + memory_analysis bytes, and predict the step
    time against the TPU DeviceSpec (cxxnet_tpu/utils/perf.py — the
    generation PALLAS_AXON_TPU_GEN names). The tunnel being down nulls
    the MEASURED side only; these stay non-null so bench_compare and
    roofline keep an analytic trajectory across unreachable rounds."""
    import jax
    from cxxnet_tpu.io.data import DataBatch
    from cxxnet_tpu.models import alexnet_trainer
    from cxxnet_tpu.utils import perf

    batch = 256
    tr = alexnet_trainer(batch_size=batch, input_hw=227, dev="cpu",
                         extra_cfg=BF16)
    rs = np.random.RandomState(0)
    b = DataBatch()
    b.data = rs.rand(batch, 3, 227, 227).astype(np.float32)
    b.label = rs.randint(0, 1000, (batch, 1)).astype(np.float32)
    b.batch_size = batch
    lowered = tr.lower_update(b)
    cost = lowered.cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    m = lowered.compile().memory_analysis()
    spec = perf.offline_spec()
    flops = cost.get("flops")
    # the ledger's own card math (ONE definition of the bound and the
    # footprint — bench rows and /programz cannot drift apart)
    pred = perf.predicted_seconds(flops, cost.get("bytes accessed"),
                                  spec)
    return {
        "predicted_step_ms": round(1e3 * pred, 4) if pred is not None
        else None,
        "hbm_peak_bytes": perf.footprint_bytes(m),
        "mfu_pct": None,            # needs a measured rate
        "analytic": {"model": model, "batch": batch,
                     "flops_per_step": flops, "spec": spec.name,
                     "note": "CPU-lowered cost/memory analysis; "
                             "predicted vs %s peaks" % spec.name},
    }


def _analytic_subprocess(timeout=240):
    """Run the analytic compute in a bounded CPU child (the parent must
    not import jax — a preloaded tunnel platform hangs); None on any
    failure, never an exception."""
    import subprocess
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               CXXNET_JAX_PLATFORM="cpu")
    env.pop("_CXXNET_BENCH_CHILD", None)
    try:
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "analytic"],
            capture_output=True, timeout=timeout, env=env)
        if p.returncode != 0:
            return None
        for line in reversed(p.stdout.decode().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                return json.loads(line)
    except Exception:
        pass
    return None


def _probe_backend(attempts=4, probe_timeout=45, sleep_s=30):
    """The axon TPU tunnel can be down for stretches (jax then HANGS rather
    than erroring). Probe it in a bounded subprocess with a few short
    retries; the caller FAILS FAST with a structured error line if the
    backend never answers — never 'proceed anyway' into a hang."""
    import subprocess
    attempts = int(os.environ.get("CXXNET_BENCH_PROBE_ATTEMPTS", attempts))
    for i in range(attempts):
        try:
            p = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                capture_output=True, timeout=probe_timeout)
            if p.returncode == 0:
                return True
        except subprocess.TimeoutExpired:
            pass
        if i + 1 < attempts:
            print("backend unreachable (attempt %d/%d); retrying in %ds"
                  % (i + 1, attempts, sleep_s), file=sys.stderr, flush=True)
            time.sleep(sleep_s)
    return False


def _attach_perf(result):
    """Fold the performance ledger's card for the row's main program
    into the bench line: ``predicted_step_ms`` (roofline), ``mfu_pct``
    (vs the measured step histogram), ``hbm_peak_bytes`` (XLA per-device
    footprint). The ANALYTIC fields stay non-null wherever a program
    compiled — including CPU runs with the TPU tunnel down — which is
    what keeps the perf trajectory's denominator visible across null
    rounds (tools/bench_compare.py gates the sub-fields opt-in)."""
    from cxxnet_tpu.utils import perf
    lg = perf.ledger()
    if not lg.enabled:
        return result
    lg.drain(20.0)
    snap = lg.snapshot()
    card = None
    # the row's main program: train rows compiled a train step; decode
    # rows a decode scan; inference rows a predict program
    for name in ("jit.train_step", "jit.decode_step", "jit.predict"):
        ready = [c for c in snap["cards"]
                 if c["name"] == name and c["status"] == "ready"]
        if ready:
            card = ready[-1]
            break
    if card is not None:
        result["predicted_step_ms"] = (
            round(card["predicted_s"] * 1e3, 4)
            if card["predicted_s"] is not None else None)
        result["hbm_peak_bytes"] = card["peak_bytes"]
        result["mfu_pct"] = card["mfu_pct"]
    lg.reset()
    return result


def _attach_telemetry(result):
    """Fold the per-phase telemetry breakdown (top spans, compile count/
    seconds, counters since the last bench) into a bench line, so
    BENCH_*.json carries the breakdown instead of one opaque number.
    The step-time HISTOGRAM percentiles (fixed log-spaced buckets, the
    same series /metrics scrapes live) ride along as "step_ms" — the
    p50/p90/p99 tail a mean-throughput number hides."""
    from cxxnet_tpu.utils import telemetry
    if telemetry.enabled():
        # the ledger joins the measured histograms, so it reads BEFORE
        # the reset below wipes them
        _attach_perf(result)
        # one summary() pass feeds both views (it sorts every span's
        # duration history — don't do that twice per bench line)
        s = telemetry.summary()
        result["telemetry"] = telemetry.brief_summary(summary=s)
        h = s.get("hists", {}).get("train.step")
        if h and h["count"]:
            result["step_ms"] = {"p50": h["p50_ms"], "p90": h["p90_ms"],
                                 "p99": h["p99_ms"]}
        telemetry.reset()
    return result


def _bench_main():
    from cxxnet_tpu.utils import enable_compile_cache, perf, telemetry
    enable_compile_cache()
    # in-memory telemetry (no JSONL sink): each bench line gets the
    # spans/compiles recorded during ITS run attached by _attach_telemetry
    telemetry.enable()
    # the program ledger: every bench row's compiled programs get
    # cost/memory cards -> predicted_step_ms / mfu_pct / hbm_peak_bytes
    perf.enable()
    if len(sys.argv) > 1 and sys.argv[1] == "all":
        for fn in (bench_mnist_mlp, bench_mnist_conv, bench_bowl,
                   bench_googlenet, bench_googlenet_b256,
                   bench_resnet, bench_vgg, bench_mobilenet,
                   bench_transformer_lm, bench_transformer_lm_long,
                   bench_vit, bench_alexnet_b1024, bench_alexnet_infer,
                   bench_alexnet_latency_b1, bench_lm_decode,
                   bench_lm_decode_b1, bench_lm_decode_long,
                   bench_lm_decode_chunked, bench_lm_decode_long_chunked,
                   bench_lm_decode_b1_chunked, bench_serve_load,
                   bench_serve_throughput, bench_serve_prefix_reuse,
                   bench_serve_multiturn_ttft,
                   bench_serve_fleet,
                   bench_serve_tenant_isolation,
                   bench_serve_chaos_availability,
                   bench_serve_hedged_tail):
            print(json.dumps(_attach_telemetry(fn())), flush=True)
        # the cold-start family shares one run (one trainer, three
        # rows) — list-returning, like the pipeline rows below
        for line in bench_serve_cold_start():
            print(json.dumps(_attach_telemetry(line)), flush=True)
    if len(sys.argv) > 1 and sys.argv[1] in ("all", "pipeline"):
        lines = bench_alexnet_pipeline()
        if lines:
            _attach_telemetry(lines[-1])
        for line in lines:
            print(json.dumps(line), flush=True)
    # default (driver) mode: exactly ONE JSON line
    print(json.dumps(_attach_telemetry(bench_alexnet())), flush=True)


def main():
    """Probe, then run the measurements in a watchdogged child process.

    Two failure modes become structured one-line JSON errors + nonzero
    exit instead of hangs: (a) backend unreachable at start (tunnel
    down), (b) backend wedges MID-RUN (child exceeds the watchdog)."""
    import signal
    import subprocess
    if os.environ.get("_CXXNET_BENCH_CHILD") == "1":
        _bench_main()
        return
    if len(sys.argv) > 1 and sys.argv[1] == "io":
        # host-side feed bench: no device, no tunnel, no probe/watchdog
        os.environ.setdefault("CXXNET_JAX_PLATFORM", "cpu")
        for line in bench_alexnet_pipeline(io_only=True):
            print(json.dumps(line), flush=True)
        return
    if len(sys.argv) > 1 and sys.argv[1] == "analytic":
        # CPU-side analytic fields only (no device, no probe): the mode
        # the unreachable path shells out to, also directly invocable
        import jax
        jax.config.update("jax_platforms", "cpu")
        print(json.dumps(_analytic_fields()), flush=True)
        return
    t0 = time.perf_counter()
    if not _probe_backend():
        print("backend unreachable; failing fast", file=sys.stderr,
              flush=True)
        # the measured side is gone; the ANALYTIC side is not — a CPU
        # child lowers the same step and predicts against the chip spec
        print(_error_line("backend unreachable (TPU tunnel down)",
                          extra=_analytic_subprocess()),
              flush=True)
        sys.exit(1)
    # watchdog budget scales with the mode and sits BELOW the outer
    # timeouts tools/onchip_queue.sh allots each step, so the structured
    # error line is emitted before any outer kill fires; probe retries
    # spend from the same budget (the outer clock started with them)
    mode = sys.argv[1] if len(sys.argv) > 1 else ""
    limit = int(os.environ.get(
        "CXXNET_BENCH_TIMEOUT",
        {"all": 5100, "pipeline": 1080}.get(mode, 780)))
    limit = max(min(limit, 60), limit - int(time.perf_counter() - t0))
    env = dict(os.environ, _CXXNET_BENCH_CHILD="1")
    proc = subprocess.Popen([sys.executable] + sys.argv, env=env)

    def _reap(msg):
        proc.terminate()
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
        print(_error_line(msg), flush=True)
        sys.exit(1)

    # an outer `timeout` (e.g. the on-chip queue's) signals only this
    # parent — reap the TPU-holding child so it can't run concurrently
    # with the queue's next step and wedge the tunnel
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda s, f: _reap("bench killed by signal"))
    try:
        rc = proc.wait(timeout=limit)
    except subprocess.TimeoutExpired:
        _reap("bench exceeded %ds watchdog (backend wedged mid-run?)"
              % limit)
    sys.exit(rc)


if __name__ == "__main__":
    main()
