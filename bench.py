"""Benchmark harness: AlexNet ImageNet-shape training throughput on TPU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline target (BASELINE.md): 2000 images/sec/chip on AlexNet.

Measures the steady-state train step (forward + backward + SGD update on the
reference AlexNet recipe, batch 256, 3x227x227, f32) with device-resident
input — the input pipeline overlaps H2D via the threadbuffer prefetcher in
real training, and per-step train metrics are off (eval_train=0) as they
would be for a throughput run. The final value fetch forces full device sync
so async dispatch cannot inflate the number.
"""

import json
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    from cxxnet_tpu.models import alexnet_trainer
    from cxxnet_tpu.io.data import DataBatch

    batch = 256
    # bf16 mixed precision is the TPU-native recipe: activations and layer
    # params run the MXU's native dtype, master weights/optimizer stay f32
    tr = alexnet_trainer(batch_size=batch, input_hw=227, dev="tpu",
                         extra_cfg="eval_train = 0\n"
                                   "compute_dtype = bfloat16\n")

    rs = np.random.RandomState(0)
    b = DataBatch()
    # device-resident batch: steady-state assumes prefetch overlaps H2D
    b.data = jax.device_put(rs.rand(batch, 3, 227, 227).astype(np.float32))
    b.label = jax.device_put(
        rs.randint(0, 1000, (batch, 1)).astype(np.float32))
    b.batch_size = batch

    # warmup / compile
    for _ in range(3):
        tr.update(b)
    float(jnp.sum(tr.params[0]["bias"]))  # full sync

    steps = 30
    t0 = time.perf_counter()
    for _ in range(steps):
        tr.update(b)
    float(jnp.sum(tr.params[0]["bias"]))  # full sync
    dt = time.perf_counter() - t0

    ips = steps * batch / dt
    out = {
        "metric": "alexnet_imagenet_images_per_sec_per_chip",
        "value": round(ips, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(ips / 2000.0, 4),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
