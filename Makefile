# Builds the native runtime of cxxnet_tpu:
#   lib/libcxxnet_tpu_core.so  — config parser, BinaryPage io, threaded reader
#   bin/im2bin                 — corpus packer (tools/im2bin.cc)
# The Python package auto-loads the .so when present and falls back to the
# pure-Python implementations otherwise (cxxnet_tpu/utils/native.py).

CXX ?= g++
CXXFLAGS ?= -O2 -std=c++17 -Wall -fPIC -pthread

CORE_SRC = src/core/config.cc src/core/binary_page.cc src/core/jpeg_decode.cc
CORE_HDR = src/core/cxn_core.h
CORE_LIBS = -ljpeg

PY_INCLUDES := $(shell python3-config --includes)
PY_LDFLAGS := $(shell python3-config --ldflags --embed)

all: lib/libcxxnet_tpu_core.so bin/im2bin lib/libcxxnetwrapper.so

lib/libcxxnetwrapper.so: wrapper/cxxnet_wrapper.cc wrapper/cxxnet_wrapper.h
	@mkdir -p lib
	$(CXX) $(CXXFLAGS) $(PY_INCLUDES) -shared -o $@ wrapper/cxxnet_wrapper.cc $(PY_LDFLAGS)

bin/test_wrapper_c: wrapper/test_wrapper.c lib/libcxxnetwrapper.so
	@mkdir -p bin
	$(CC) -O2 -Wall -pthread -o $@ wrapper/test_wrapper.c -Llib -lcxxnetwrapper -Wl,-rpath,'$$ORIGIN/../lib'

lib/libcxxnet_tpu_core.so: $(CORE_SRC) $(CORE_HDR)
	@mkdir -p lib
	$(CXX) $(CXXFLAGS) -shared -o $@ $(CORE_SRC) $(CORE_LIBS)

bin/im2bin: tools/im2bin.cc $(CORE_SRC) $(CORE_HDR)
	@mkdir -p bin
	$(CXX) $(CXXFLAGS) -o $@ tools/im2bin.cc $(CORE_SRC) $(CORE_LIBS)

clean:
	rm -f lib/libcxxnet_tpu_core.so lib/libcxxnetwrapper.so bin/im2bin bin/test_wrapper_c

# tier-1 fast pass (what the driver's verify runs): the telemetry tests
# ride here unmarked — only @pytest.mark.slow tests are excluded
test-fast:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
		-p no:cacheprovider

# where the tier-1 wall-clock goes: the 15 slowest tests of the same
# selection test-fast runs — watch this when adding tests so the fast
# pass stays fast (anything that can't get under ~5s belongs behind
# @pytest.mark.slow instead)
t1-slowest:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
		-p no:cacheprovider --durations=15 --durations-min=0.5

# project-native static analysis (doc/static_analysis.md): lock-order /
# blocking-under-lock rules, JAX hazards (donated reuse, traced
# branches, wall-clock durations, dispatch-vs-compute spans), the
# conf-key doc registry and the telemetry metric registry — ratcheted
# against tools/cxxlint_baseline.json (counts may only shrink)
lint:
	python tools/cxxlint.py

# fast regression gate (no pytest, no jax): every module byte-compiles,
# the checkpoint verifier still detects every corruption class, the
# training-health detect->rollback->skip state machine still recovers,
# the live introspection service serves/scrapes/shuts-down on a real
# socket with valid Prometheus output, the serving frontend's
# admission/deadline/breaker/drain machinery answers every request over
# a real socket (both with CXXNET_LOCKRANK=1 runtime lock-order
# enforcement), and the static analyzer parses the whole package and
# agrees the tree is clean — a checkpoint-format, recovery-policy,
# metrics-format, serving-protocol, or lock-ordering regression fails
# here in seconds
check:
	python -m compileall -q cxxnet_tpu tools tests
	python tools/ckpt_fsck.py --selftest
	python -m cxxnet_tpu.utils.health --selftest
	python -m cxxnet_tpu.utils.statusd --selftest
	python -m cxxnet_tpu.utils.servd --selftest
	python -m cxxnet_tpu.utils.routerd --selftest
	python -m cxxnet_tpu.utils.perf --selftest
	python -c "import sys; from cxxnet_tpu.utils import lockrank; \
		sys.exit(lockrank.selftest(verbose=True))"
	python tools/cxxlint.py --selftest

.PHONY: all clean test-fast t1-slowest check lint
