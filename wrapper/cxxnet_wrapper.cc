/*!
 * cxxnet_wrapper.cc — C ABI over cxxnet_tpu.api via an embedded CPython.
 *
 * Handle model: every void* is a `Handle` owning a PyObject (api.DataIter or
 * api.Net) plus the buffers of the last returned array/string, so borrowed
 * pointers stay valid until the next call on the same handle (the
 * reference's temp-buffer convention, wrapper/cxxnet_wrapper.cpp:10-76).
 *
 * Threading: every entry point takes the GIL (PyGILState_Ensure); the
 * interpreter is initialized lazily on the first call.
 */
#include "cxxnet_wrapper.h"

#include <Python.h>

#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

namespace {

std::string g_last_error;

void SetError(const std::string &msg) {
  g_last_error = msg;
  std::fprintf(stderr, "cxxnet_wrapper: %s\n", msg.c_str());
}

/* capture the active Python exception into g_last_error */
void CapturePyError(const char *where) {
  PyObject *type = nullptr, *value = nullptr, *trace = nullptr;
  PyErr_Fetch(&type, &value, &trace);
  std::string msg = where;
  if (value) {
    PyObject *s = PyObject_Str(value);
    if (s) {
      msg += ": ";
      msg += PyUnicode_AsUTF8(s);
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(trace);
  SetError(msg);
}

PyObject *g_api = nullptr;  /* module cxxnet_tpu.api */

bool EnsurePython() {
  static std::once_flag once;
  static bool ok = false;
  std::call_once(once, [] {
    bool first_init = false;
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      first_init = true;
    }
    PyGILState_STATE gil = PyGILState_Ensure();
    const char *bootstrap =
        "import os, sys\n"
        "_root = os.environ.get('CXXNET_TPU_ROOT', os.getcwd())\n"
        "if _root not in sys.path:\n"
        "    sys.path.insert(0, _root)\n"
        "_plat = os.environ.get('CXXNET_JAX_PLATFORM')\n"
        "if _plat:\n"
        "    import jax\n"
        "    jax.config.update('jax_platforms', _plat)\n";
    if (PyRun_SimpleString(bootstrap) != 0) {
      SetError("bootstrap failed");
    } else {
      g_api = PyImport_ImportModule("cxxnet_tpu.api");
      if (!g_api) {
        CapturePyError("import cxxnet_tpu.api");
      } else {
        ok = true;
      }
    }
    PyGILState_Release(gil);
    /* Py_InitializeEx leaves the GIL held by the initializing thread. If we
       did the init, hand it back so (a) GilGuard entry points work from any
       embedder thread and (b) Python worker threads (imgbinx decode pool)
       run while the host app is outside wrapper calls. An embedder that
       initialized Python itself manages its own GIL — don't touch it. */
    if (first_init) (void)PyEval_SaveThread();
  });
  return ok;
}

struct Handle {
  PyObject *obj = nullptr;      /* the api.DataIter / api.Net */
  PyObject *last_array = nullptr;
  Py_buffer last_buf{};
  bool has_buf = false;
  std::string last_str;

  void DropBuf() {
    if (has_buf) {
      PyBuffer_Release(&last_buf);
      has_buf = false;
    }
    Py_CLEAR(last_array);
  }
  ~Handle() {
    PyGILState_STATE gil = PyGILState_Ensure();
    DropBuf();
    Py_CLEAR(obj);
    PyGILState_Release(gil);
  }
};

struct GilGuard {
  PyGILState_STATE state;
  GilGuard() : state(PyGILState_Ensure()) {}
  ~GilGuard() { PyGILState_Release(state); }
};

/* call obj.method(*args); returns new ref or NULL with error captured */
PyObject *Call(PyObject *obj, const char *method, PyObject *args) {
  PyObject *fn = PyObject_GetAttrString(obj, method);
  if (!fn) {
    CapturePyError(method);
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject *ret = PyObject_CallObject(fn, args);
  Py_DECREF(fn);
  Py_XDECREF(args);
  if (!ret) CapturePyError(method);
  return ret;
}

/* wrap a C float buffer as a numpy array (copy) with the given shape */
PyObject *MakeArray(const cxn_real_t *data, const cxn_uint *shape, int ndim) {
  Py_ssize_t total = 1;
  for (int i = 0; i < ndim; ++i) total *= shape[i];
  PyObject *np = PyImport_ImportModule("numpy");
  if (!np) {
    CapturePyError("import numpy");
    return nullptr;
  }
  PyObject *mem = PyMemoryView_FromMemory(
      reinterpret_cast<char *>(const_cast<cxn_real_t *>(data)),
      total * Py_ssize_t(sizeof(cxn_real_t)), PyBUF_READ);
  PyObject *frombuffer = PyObject_GetAttrString(np, "frombuffer");
  PyObject *arr = nullptr;
  if (mem && frombuffer) {
    PyObject *args = Py_BuildValue("(O)", mem);
    PyObject *kw = Py_BuildValue("{s:s}", "dtype", "float32");
    PyObject *flat = PyObject_Call(frombuffer, args, kw);
    Py_DECREF(args);
    Py_DECREF(kw);
    if (flat) {
      PyObject *shp = PyTuple_New(ndim);
      for (int i = 0; i < ndim; ++i)
        PyTuple_SET_ITEM(shp, i, PyLong_FromLong(long(shape[i])));
      PyObject *view = Call(flat, "reshape", Py_BuildValue("(O)", shp));
      Py_DECREF(shp);
      Py_DECREF(flat);
      if (view) {
        /* the trainer dispatches asynchronously (device_put may read the
           host buffer after this call returns), so the array must own its
           data — the ABI lets the caller free the buffer immediately */
        arr = Call(view, "copy", PyTuple_New(0));
        Py_DECREF(view);
      }
    } else {
      CapturePyError("numpy.frombuffer");
    }
  }
  Py_XDECREF(frombuffer);
  Py_XDECREF(mem);
  Py_DECREF(np);
  return arr;
}

/* expose a numpy array's float data on the handle; fills shape_out[0..ndim)
 * padded with the flattened trailing dims when the array has more dims */
const cxn_real_t *ExposeArray(Handle *h, PyObject *arr, cxn_uint *shape_out,
                              int want_dim, cxn_uint *out_total) {
  h->DropBuf();
  /* force float32 C-contiguous */
  PyObject *np = PyImport_ImportModule("numpy");
  if (!np) {
    CapturePyError("import numpy");
    Py_DECREF(arr);
    return nullptr;
  }
  PyObject *asc = PyObject_GetAttrString(np, "ascontiguousarray");
  PyObject *args = Py_BuildValue("(O)", arr);
  PyObject *kw = Py_BuildValue("{s:s}", "dtype", "float32");
  PyObject *carr = asc ? PyObject_Call(asc, args, kw) : nullptr;
  Py_XDECREF(asc);
  Py_DECREF(args);
  Py_DECREF(kw);
  Py_DECREF(np);
  Py_DECREF(arr);
  if (!carr) {
    CapturePyError("ascontiguousarray");
    return nullptr;
  }
  if (PyObject_GetBuffer(carr, &h->last_buf,
                         PyBUF_C_CONTIGUOUS | PyBUF_FORMAT) != 0) {
    CapturePyError("GetBuffer");
    Py_DECREF(carr);
    return nullptr;
  }
  h->has_buf = true;
  h->last_array = carr;
  if (shape_out) {
    for (int i = 0; i < want_dim; ++i) shape_out[i] = 1;
    int nd = int(h->last_buf.ndim);
    for (int i = 0; i < nd && i < want_dim; ++i)
      shape_out[i] = cxn_uint(h->last_buf.shape[i]);
    if (nd > want_dim) { /* flatten extras into the last reported dim */
      for (int i = want_dim; i < nd; ++i)
        shape_out[want_dim - 1] *= cxn_uint(h->last_buf.shape[i]);
    }
  }
  if (out_total)
    *out_total = cxn_uint(h->last_buf.len / Py_ssize_t(sizeof(cxn_real_t)));
  return reinterpret_cast<const cxn_real_t *>(h->last_buf.buf);
}

}  // namespace

extern "C" const char *CXNGetLastError(void) { return g_last_error.c_str(); }

/* ---------------- iterator ---------------- */

extern "C" void *CXNIOCreateFromConfig(const char *cfg) {
  if (!EnsurePython()) return nullptr;
  GilGuard gil;
  PyObject *cls = PyObject_GetAttrString(g_api, "DataIter");
  if (!cls) {
    CapturePyError("DataIter");
    return nullptr;
  }
  PyObject *obj = PyObject_CallFunction(cls, "s", cfg);
  Py_DECREF(cls);
  if (!obj) {
    CapturePyError("DataIter()");
    return nullptr;
  }
  Handle *h = new Handle();
  h->obj = obj;
  return h;
}

extern "C" int CXNIONext(void *handle) {
  GilGuard gil;
  Handle *h = static_cast<Handle *>(handle);
  PyObject *r = Call(h->obj, "next", nullptr);
  if (!r) return -1;
  int ret = PyObject_IsTrue(r);
  Py_DECREF(r);
  return ret;
}

extern "C" int CXNIOBeforeFirst(void *handle) {
  GilGuard gil;
  Handle *h = static_cast<Handle *>(handle);
  PyObject *r = Call(h->obj, "before_first", nullptr);
  if (!r) return -1;
  Py_DECREF(r);
  return 0;
}

extern "C" const cxn_real_t *CXNIOGetData(void *handle, cxn_uint oshape[4]) {
  GilGuard gil;
  Handle *h = static_cast<Handle *>(handle);
  PyObject *arr = Call(h->obj, "get_data", nullptr);
  if (!arr) return nullptr;
  return ExposeArray(h, arr, oshape, 4, nullptr);
}

extern "C" const cxn_real_t *CXNIOGetLabel(void *handle, cxn_uint oshape[2]) {
  GilGuard gil;
  Handle *h = static_cast<Handle *>(handle);
  PyObject *arr = Call(h->obj, "get_label", nullptr);
  if (!arr) return nullptr;
  return ExposeArray(h, arr, oshape, 2, nullptr);
}

extern "C" void CXNIOFree(void *handle) {
  delete static_cast<Handle *>(handle);
}

/* ---------------- net ---------------- */

extern "C" void *CXNNetCreate(const char *device, const char *cfg) {
  if (!EnsurePython()) return nullptr;
  GilGuard gil;
  PyObject *cls = PyObject_GetAttrString(g_api, "Net");
  if (!cls) {
    CapturePyError("Net");
    return nullptr;
  }
  PyObject *obj = PyObject_CallFunction(cls, "ss", device ? device : "tpu",
                                        cfg ? cfg : "");
  Py_DECREF(cls);
  if (!obj) {
    CapturePyError("Net()");
    return nullptr;
  }
  Handle *h = new Handle();
  h->obj = obj;
  return h;
}

extern "C" void CXNNetFree(void *handle) {
  delete static_cast<Handle *>(handle);
}

static int SimpleCall(void *handle, const char *method, PyObject *args) {
  GilGuard gil;
  Handle *h = static_cast<Handle *>(handle);
  PyObject *r = Call(h->obj, method, args);
  if (!r) return -1;
  Py_DECREF(r);
  return 0;
}

extern "C" int CXNNetSetParam(void *handle, const char *name,
                              const char *val) {
  GilGuard gil;
  return SimpleCall(handle, "set_param", Py_BuildValue("(ss)", name, val));
}

extern "C" int CXNNetInitModel(void *handle) {
  return SimpleCall(handle, "init_model", nullptr);
}

extern "C" int CXNNetSaveModel(void *handle, const char *fname) {
  GilGuard gil;
  return SimpleCall(handle, "save_model", Py_BuildValue("(s)", fname));
}

extern "C" int CXNNetLoadModel(void *handle, const char *fname) {
  GilGuard gil;
  return SimpleCall(handle, "load_model", Py_BuildValue("(s)", fname));
}

extern "C" int CXNNetStartRound(void *handle, int round_counter) {
  GilGuard gil;
  return SimpleCall(handle, "start_round",
                    Py_BuildValue("(i)", round_counter));
}

extern "C" int CXNNetUpdateIter(void *net_handle, void *io_handle) {
  GilGuard gil;
  Handle *net = static_cast<Handle *>(net_handle);
  Handle *io = static_cast<Handle *>(io_handle);
  PyObject *r = Call(net->obj, "update", Py_BuildValue("(O)", io->obj));
  if (!r) return -1;
  Py_DECREF(r);
  return 0;
}

extern "C" int CXNNetUpdateBatch(void *handle, const cxn_real_t *data,
                                 const cxn_uint dshape[4],
                                 const cxn_real_t *label,
                                 const cxn_uint lshape[2]) {
  GilGuard gil;
  Handle *h = static_cast<Handle *>(handle);
  PyObject *darr = MakeArray(data, dshape, 4);
  if (!darr) return -1;
  PyObject *larr = Py_None;
  Py_INCREF(Py_None);
  if (label) {
    Py_DECREF(Py_None);
    larr = MakeArray(label, lshape, 2);
    if (!larr) {
      Py_DECREF(darr);
      return -1;
    }
  }
  PyObject *r = Call(h->obj, "update", Py_BuildValue("(NN)", darr, larr));
  if (!r) return -1;
  Py_DECREF(r);
  return 0;
}

extern "C" const cxn_real_t *CXNNetPredictBatch(void *handle,
                                                const cxn_real_t *data,
                                                const cxn_uint dshape[4],
                                                cxn_uint *out_size) {
  GilGuard gil;
  Handle *h = static_cast<Handle *>(handle);
  PyObject *darr = MakeArray(data, dshape, 4);
  if (!darr) return nullptr;
  PyObject *arr = Call(h->obj, "predict", Py_BuildValue("(N)", darr));
  if (!arr) return nullptr;
  cxn_uint shape1[1] = {0};
  const cxn_real_t *p = ExposeArray(h, arr, shape1, 1, nullptr);
  if (out_size) *out_size = shape1[0];
  return p;
}

extern "C" const cxn_real_t *CXNNetPredictIter(void *net_handle,
                                               void *io_handle,
                                               cxn_uint *out_size) {
  GilGuard gil;
  Handle *net = static_cast<Handle *>(net_handle);
  Handle *io = static_cast<Handle *>(io_handle);
  PyObject *arr = Call(net->obj, "predict", Py_BuildValue("(O)", io->obj));
  if (!arr) return nullptr;
  cxn_uint shape1[1] = {0};
  const cxn_real_t *p = ExposeArray(net, arr, shape1, 1, nullptr);
  if (out_size) *out_size = shape1[0];
  return p;
}

extern "C" const cxn_real_t *CXNNetExtractBatch(void *handle,
                                                const cxn_real_t *data,
                                                const cxn_uint dshape[4],
                                                const char *node_name,
                                                cxn_uint oshape[2]) {
  GilGuard gil;
  Handle *h = static_cast<Handle *>(handle);
  PyObject *darr = MakeArray(data, dshape, 4);
  if (!darr) return nullptr;
  PyObject *arr = Call(h->obj, "extract",
                       Py_BuildValue("(Ns)", darr, node_name));
  if (!arr) return nullptr;
  return ExposeArray(h, arr, oshape, 2, nullptr);
}

extern "C" const cxn_real_t *CXNNetExtractIter(void *net_handle,
                                               void *io_handle,
                                               const char *node_name,
                                               cxn_uint oshape[2]) {
  GilGuard gil;
  Handle *net = static_cast<Handle *>(net_handle);
  Handle *io = static_cast<Handle *>(io_handle);
  PyObject *arr = Call(net->obj, "extract",
                       Py_BuildValue("(Os)", io->obj, node_name));
  if (!arr) return nullptr;
  return ExposeArray(net, arr, oshape, 2, nullptr);
}

extern "C" const cxn_real_t *CXNNetGenerate(void *handle,
                                            const cxn_real_t *prompts,
                                            const cxn_uint pshape[2],
                                            cxn_uint n_new,
                                            float temperature,
                                            cxn_uint top_k, cxn_uint seed,
                                            cxn_uint oshape[2]) {
  GilGuard gil;
  Handle *h = static_cast<Handle *>(handle);
  PyObject *parr = MakeArray(prompts, pshape, 2);
  if (!parr) return nullptr;
  /* api.Net.generate(prompts, n_new, temperature, top_k, seed) — float
   * ids in, float ids out (ExposeArray re-encodes the int result) */
  PyObject *arr = Call(h->obj, "generate",
                       Py_BuildValue("(NIfII)", parr, n_new,
                                     (double)temperature, top_k, seed));
  if (!arr) return nullptr;
  return ExposeArray(h, arr, oshape, 2, nullptr);
}

extern "C" const char *CXNNetEvaluate(void *net_handle, void *io_handle,
                                      const char *data_name) {
  GilGuard gil;
  Handle *net = static_cast<Handle *>(net_handle);
  Handle *io = static_cast<Handle *>(io_handle);
  PyObject *r = Call(net->obj, "evaluate",
                     Py_BuildValue("(Os)", io->obj, data_name));
  if (!r) return nullptr;
  const char *s = PyUnicode_AsUTF8(r);
  net->last_str = s ? s : "";
  Py_DECREF(r);
  return net->last_str.c_str();
}

extern "C" int CXNNetSetWeight(void *handle, const cxn_real_t *weight,
                               const cxn_uint wshape[2],
                               const char *layer_name, const char *tag) {
  GilGuard gil;
  Handle *h = static_cast<Handle *>(handle);
  PyObject *warr = MakeArray(weight, wshape, 2);
  if (!warr) return -1;
  PyObject *r = Call(h->obj, "set_weight",
                     Py_BuildValue("(Nss)", warr, layer_name, tag));
  if (!r) return -1;
  Py_DECREF(r);
  return 0;
}

extern "C" const cxn_real_t *CXNNetGetWeight(void *handle,
                                             const char *layer_name,
                                             const char *tag,
                                             cxn_uint oshape[2]) {
  GilGuard gil;
  Handle *h = static_cast<Handle *>(handle);
  PyObject *arr = Call(h->obj, "get_weight",
                       Py_BuildValue("(ss)", layer_name, tag));
  if (!arr) return nullptr;
  return ExposeArray(h, arr, oshape, 2, nullptr);
}
