/*!
 * cxxnet_wrapper.h — handle-based C ABI of cxxnet_tpu.
 *
 * Counterpart of the reference C API (reference: wrapper/cxxnet_wrapper.h:
 * 36-230): iterator and net handles created from config strings, update from
 * an iterator or raw row-major float batches, predict/extract returning
 * borrowed float buffers (valid until the next call on the same handle),
 * evaluate returning a string, and weight get/set.
 *
 * Since the compute path is JAX, the library embeds a CPython interpreter
 * and drives cxxnet_tpu.api — one implementation behind both the Python and
 * the C surface. Environment knobs read at first call:
 *   CXXNET_TPU_ROOT       repo/package root to put on sys.path (default cwd)
 *   CXXNET_JAX_PLATFORM   optional jax platform override (e.g. "cpu")
 *
 * All functions return NULL / a negative count on error; the message is
 * printed to stderr and retrievable via CXNGetLastError().
 */
#ifndef CXXNET_WRAPPER_H_
#define CXXNET_WRAPPER_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef float cxn_real_t;
typedef uint32_t cxn_uint;

const char *CXNGetLastError(void);

/* ---- data iterator ---- */
void *CXNIOCreateFromConfig(const char *cfg);
/*! move to next batch; 1 = has batch, 0 = end of epoch, -1 = error */
int CXNIONext(void *handle);
int CXNIOBeforeFirst(void *handle);
/*! current batch data; writes the 4-D shape; buffer lives until next call */
const cxn_real_t *CXNIOGetData(void *handle, cxn_uint oshape[4]);
/*! current batch labels; writes (batch, label_width) */
const cxn_real_t *CXNIOGetLabel(void *handle, cxn_uint oshape[2]);
void CXNIOFree(void *handle);

/* ---- net ---- */
void *CXNNetCreate(const char *device, const char *cfg);
void CXNNetFree(void *handle);
int CXNNetSetParam(void *handle, const char *name, const char *val);
int CXNNetInitModel(void *handle);
int CXNNetSaveModel(void *handle, const char *fname);
int CXNNetLoadModel(void *handle, const char *fname);
int CXNNetStartRound(void *handle, int round_counter);
/*! one update step on the iterator's current batch */
int CXNNetUpdateIter(void *net_handle, void *io_handle);
/*! one update step on a raw batch: data is row-major (dshape), labels
 *  (lshape[0], lshape[1]); label may be NULL for unlabeled nets */
int CXNNetUpdateBatch(void *handle, const cxn_real_t *data,
                      const cxn_uint dshape[4], const cxn_real_t *label,
                      const cxn_uint lshape[2]);
/*! per-row predictions; *out_size rows; buffer lives until next call */
const cxn_real_t *CXNNetPredictBatch(void *handle, const cxn_real_t *data,
                                     const cxn_uint dshape[4],
                                     cxn_uint *out_size);
const cxn_real_t *CXNNetPredictIter(void *net_handle, void *io_handle,
                                    cxn_uint *out_size);
/*! named-node activations flattened to (batch, feat); writes both dims */
const cxn_real_t *CXNNetExtractBatch(void *handle, const cxn_real_t *data,
                                     const cxn_uint dshape[4],
                                     const char *node_name,
                                     cxn_uint oshape[2]);
const cxn_real_t *CXNNetExtractIter(void *net_handle, void *io_handle,
                                    const char *node_name,
                                    cxn_uint oshape[2]);
/*! KV-cached generation for sequence nets (beyond the reference ABI —
 *  the serving loop of Trainer.generate): ``prompts`` is a row-major
 *  (batch, prompt_len) matrix of token ids encoded as floats; returns a
 *  borrowed (batch, n_new) matrix of generated ids (float-encoded,
 *  exact for vocabularies < 2^24) and fills oshape. Greedy when
 *  temperature == 0; temperature/top_k/seed select sampling. */
const cxn_real_t *CXNNetGenerate(void *handle, const cxn_real_t *prompts,
                                 const cxn_uint pshape[2], cxn_uint n_new,
                                 float temperature, cxn_uint top_k,
                                 cxn_uint seed, cxn_uint oshape[2]);
/*! run metrics over an eval iterator; string lives until next call */
const char *CXNNetEvaluate(void *net_handle, void *io_handle,
                           const char *data_name);
int CXNNetSetWeight(void *handle, const cxn_real_t *weight,
                    const cxn_uint wshape[2], const char *layer_name,
                    const char *tag);
/*! weight as 2-D (out, in-flat); writes the dims */
const cxn_real_t *CXNNetGetWeight(void *handle, const char *layer_name,
                                  const char *tag, cxn_uint oshape[2]);

#ifdef __cplusplus
}
#endif
#endif /* CXXNET_WRAPPER_H_ */
