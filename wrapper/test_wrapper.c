/*
 * test_wrapper.c — C smoke test of the embedded-interpreter ABI.
 *
 * Builds a small MLP from a config string, memorizes one random batch,
 * checks predictions, round-trips weights and a model file. Exits 0 on
 * success, prints FAIL + nonzero otherwise. Run with CXXNET_TPU_ROOT set
 * to the repo and (optionally) CXXNET_JAX_PLATFORM=cpu.
 */
#define _GNU_SOURCE /* pthread_timedjoin_np */
#include "cxxnet_wrapper.h"

#include <pthread.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>

#define CHECK(cond, msg)                                   \
  do {                                                     \
    if (!(cond)) {                                         \
      fprintf(stderr, "FAIL: %s (%s)\n", msg,              \
              CXNGetLastError());                          \
      return 1;                                            \
    }                                                      \
  } while (0)

static const char *kNetCfg =
    "netconfig = start\n"
    "layer[+1:fc1] = fullc:fc1\n"
    "  nhidden = 32\n"
    "  init_sigma = 0.05\n"
    "layer[+1] = relu\n"
    "layer[+1:fc2] = fullc:fc2\n"
    "  nhidden = 10\n"
    "  init_sigma = 0.05\n"
    "layer[+0] = softmax\n"
    "netconfig = end\n"
    "input_shape = 1,1,64\n"
    "batch_size = 20\n"
    "eta = 0.1\n"
    "momentum = 0.9\n"
    "metric = error\n";

static int run_batch_leg(void) {
  const int kBatch = 20, kFeat = 64;
  cxn_real_t data[20 * 64];
  cxn_real_t label[20];
  unsigned seed = 9;
  for (int i = 0; i < kBatch * kFeat; ++i) {
    seed = seed * 1103515245u + 12345u;
    data[i] = (cxn_real_t)((seed >> 16) & 0x7fff) / 32768.0f;
  }
  for (int i = 0; i < kBatch; ++i) {
    seed = seed * 1103515245u + 12345u;
    label[i] = (cxn_real_t)((seed >> 16) % 10);
  }
  const cxn_uint dshape[4] = {20, 1, 1, 64};
  const cxn_uint lshape[2] = {20, 1};

  void *net = CXNNetCreate("cpu", kNetCfg);
  CHECK(net != NULL, "CXNNetCreate");
  CHECK(CXNNetInitModel(net) == 0, "InitModel");
  CHECK(CXNNetStartRound(net, 0) == 0, "StartRound");

  for (int step = 0; step < 200; ++step)
    CHECK(CXNNetUpdateBatch(net, data, dshape, label, lshape) == 0,
          "UpdateBatch");

  cxn_uint npred = 0;
  const cxn_real_t *pred_view = CXNNetPredictBatch(net, data, dshape, &npred);
  CHECK(pred_view != NULL && npred == 20, "PredictBatch");
  /* borrowed pointer only lives until the next call on this handle — copy */
  cxn_real_t pred[20];
  memcpy(pred, pred_view, sizeof(pred));
  int correct = 0;
  for (int i = 0; i < kBatch; ++i)
    if (pred[i] == label[i]) ++correct;
  fprintf(stderr, "memorized %d/20\n", correct);
  CHECK(correct >= 18, "should memorize the fixed batch");

  /* extract: softmax output rows sum to 1 */
  cxn_uint eshape[2] = {0, 0};
  const cxn_real_t *feat = CXNNetExtractBatch(net, data, dshape, "top[-1]",
                                              eshape);
  CHECK(feat != NULL && eshape[0] == 20 && eshape[1] == 10, "ExtractBatch");
  for (int i = 0; i < kBatch; ++i) {
    float s = 0;
    for (int j = 0; j < 10; ++j) s += feat[i * 10 + j];
    CHECK(s > 0.99f && s < 1.01f, "softmax rows must sum to 1");
  }

  /* weight round trip */
  cxn_uint wshape[2] = {0, 0};
  const cxn_real_t *w = CXNNetGetWeight(net, "fc1", "wmat", wshape);
  CHECK(w != NULL && wshape[0] == 32 && wshape[1] == 64, "GetWeight");
  cxn_real_t *wcopy = (cxn_real_t *)malloc(sizeof(cxn_real_t) * 32 * 64);
  memcpy(wcopy, w, sizeof(cxn_real_t) * 32 * 64);
  CHECK(CXNNetSetWeight(net, wcopy, wshape, "fc1", "wmat") == 0, "SetWeight");

  /* model file round trip: same predictions after load */
  CHECK(CXNNetSaveModel(net, "/tmp/cxn_wrapper_test.model") == 0,
        "SaveModel");
  void *net2 = CXNNetCreate("cpu", "");
  CHECK(net2 != NULL, "CXNNetCreate 2");
  CHECK(CXNNetLoadModel(net2, "/tmp/cxn_wrapper_test.model") == 0,
        "LoadModel");
  cxn_uint npred2 = 0;
  const cxn_real_t *pred2 = CXNNetPredictBatch(net2, data, dshape, &npred2);
  CHECK(pred2 != NULL && npred2 == 20, "PredictBatch 2");
  for (int i = 0; i < kBatch; ++i)
    CHECK(pred[i] == pred2[i], "prediction mismatch after load");
  free(wcopy);
  CXNNetFree(net2);
  CXNNetFree(net);
  fprintf(stderr, "C WRAPPER SMOKE TEST PASSED\n");
  return 0;
}

/* Second-thread leg: the ABI promises every entry point takes the GIL, so a
 * thread other than the one that initialized Python must be able to call in
 * (the embedded interpreter hands the GIL back after bootstrap). A hang here
 * means the init thread never released its base GIL hold. */
struct thread_arg {
  void *net;
  const cxn_real_t *data;
  const cxn_uint *dshape;
  int ok;
};

static void *predict_thread(void *p) {
  struct thread_arg *a = (struct thread_arg *)p;
  cxn_uint npred = 0;
  const cxn_real_t *pred = CXNNetPredictBatch(a->net, a->data, a->dshape,
                                              &npred);
  a->ok = (pred != NULL && npred == a->dshape[0]);
  return NULL;
}

static int run_thread_leg(void) {
  const int kBatch = 20, kFeat = 64;
  static cxn_real_t data[20 * 64];
  for (int i = 0; i < kBatch * kFeat; ++i)
    data[i] = (cxn_real_t)(i % 97) / 97.0f;
  const cxn_uint dshape[4] = {20, 1, 1, 64};

  void *net = CXNNetCreate("cpu", kNetCfg);
  CHECK(net != NULL, "CXNNetCreate (thread leg)");
  CHECK(CXNNetInitModel(net) == 0, "InitModel (thread leg)");

  struct thread_arg arg = {net, data, dshape, 0};
  pthread_t th;
  CHECK(pthread_create(&th, NULL, predict_thread, &arg) == 0,
        "pthread_create");
#ifdef __GLIBC__
  struct timespec deadline;
  clock_gettime(CLOCK_REALTIME, &deadline);
  deadline.tv_sec += 120;
  CHECK(pthread_timedjoin_np(th, NULL, &deadline) == 0,
        "second thread deadlocked in wrapper entry point (GIL not released "
        "after init)");
#else
  /* no timed join outside glibc; a regression here hangs instead of failing */
  CHECK(pthread_join(th, NULL) == 0, "pthread_join");
#endif
  CHECK(arg.ok, "predict from second thread");
  CXNNetFree(net);
  fprintf(stderr, "C WRAPPER THREAD LEG PASSED\n");
  return 0;
}

/* Serving leg: a tiny causal-attention LM trains a few steps, then
 * CXNNetGenerate continues two prompts KV-cached — the decode surface
 * the reference ABI never had. Ids ride the float ABI (exact < 2^24). */
static int run_generate_leg(void) {
  static const char *kLmCfg =
      "netconfig = start\n"
      "layer[0->1] = embed:emb\n"
      "  vocab_size = 12\n"
      "  nhidden = 16\n"
      "  pos_embed = 1\n"
      "  init_sigma = 0.05\n"
      "layer[1->2,3] = split\n"
      "layer[2->4] = attention:att1\n"
      "  nhead = 4\n"
      "  causal = 1\n"
      "  init_sigma = 0.05\n"
      "layer[3,4->5] = add\n"
      "layer[5->6] = conv:head\n"
      "  kernel_size = 1\n"
      "  nchannel = 12\n"
      "  random_type = kaiming\n"
      "layer[6->6] = softmax\n"
      "  seq = 1\n"
      "netconfig = end\n"
      "input_shape = 1,1,16\n"
      "batch_size = 4\n"
      "label_width = 16\n"
      "label_vec[0,16) = label\n"
      "updater = adam\n"
      "eta = 0.01\n";
  const int kB = 4, kL = 16, kVocab = 12;
  void *net = CXNNetCreate("cpu", kLmCfg);
  CHECK(net != NULL, "CXNNetCreate (lm)");
  CHECK(CXNNetInitModel(net) == 0, "InitModel (lm)");
  cxn_real_t data[4 * 16], label[4 * 16];
  const cxn_uint dshape[4] = {4, 1, 1, 16};
  const cxn_uint lshape[2] = {4, 16};
  for (int step = 0; step < 10; ++step) {
    for (int r = 0; r < kB; ++r)
      for (int t = 0; t < kL; ++t) {
        data[r * kL + t] = (cxn_real_t)((r + step + t) % kVocab);
        label[r * kL + t] = (cxn_real_t)((r + step + t + 1) % kVocab);
      }
    CHECK(CXNNetUpdateBatch(net, data, dshape, label, lshape) == 0,
          "UpdateBatch (lm)");
  }
  cxn_real_t prompts[2 * 4] = {1, 2, 3, 4, 7, 8, 9, 10};
  const cxn_uint pshape[2] = {2, 4};
  cxn_uint oshape[2] = {0, 0};
  const cxn_real_t *gen =
      CXNNetGenerate(net, prompts, pshape, 5, 0.0f, 0, 0, oshape);
  CHECK(gen != NULL && oshape[0] == 2 && oshape[1] == 5, "Generate");
  for (int i = 0; i < 2 * 5; ++i)
    CHECK(gen[i] >= 0 && gen[i] < kVocab && gen[i] == (int)gen[i],
          "generated ids must be in-vocab integers");
  /* same seed/prompts reproduce */
  cxn_real_t first[2 * 5];
  memcpy(first, gen, sizeof(first));
  const cxn_real_t *gen2 =
      CXNNetGenerate(net, prompts, pshape, 5, 0.0f, 0, 0, oshape);
  CHECK(gen2 != NULL, "Generate 2");
  for (int i = 0; i < 2 * 5; ++i)
    CHECK(first[i] == gen2[i], "greedy generate must be deterministic");
  CXNNetFree(net);
  fprintf(stderr, "C WRAPPER GENERATE LEG PASSED\n");
  return 0;
}

/* Iterator-ABI leg, enabled when argv[1] = path to an mnist data dir
 * (idx .gz files named as in example/MNIST). */
static int run_iter_leg(const char *dir);

int main(int argc, char **argv) {
  int rc = run_batch_leg();
  if (rc == 0) rc = run_thread_leg();
  if (rc == 0) rc = run_generate_leg();
  if (rc == 0 && argc > 1) rc = run_iter_leg(argv[1]);
  return rc;
}

static int run_iter_leg(const char *dir) {
  char cfg[1024];
  snprintf(cfg, sizeof(cfg),
           "iter = mnist\n"
           "  path_img = \"%s/train-images-idx3-ubyte.gz\"\n"
           "  path_label = \"%s/train-labels-idx1-ubyte.gz\"\n"
           "  batch_size = 25\n"
           "iter = end\n",
           dir, dir);
  void *it = CXNIOCreateFromConfig(cfg);
  CHECK(it != NULL, "CXNIOCreateFromConfig");
  CHECK(CXNIONext(it) == 1, "CXNIONext");
  cxn_uint ds[4], ls[2];
  const cxn_real_t *d = CXNIOGetData(it, ds);
  CHECK(d != NULL && ds[0] == 25 && ds[3] == 784, "CXNIOGetData");
  const cxn_real_t *l = CXNIOGetLabel(it, ls);
  CHECK(l != NULL && ls[0] == 25 && ls[1] == 1, "CXNIOGetLabel");

  char netcfg[512];
  snprintf(netcfg, sizeof(netcfg),
           "netconfig = start\n"
           "layer[+1:fc1] = fullc:fc1\n"
           "  nhidden = 16\n"
           "  init_sigma = 0.05\n"
           "layer[+1] = relu\n"
           "layer[+1:fc2] = fullc:fc2\n"
           "  nhidden = 10\n"
           "  init_sigma = 0.05\n"
           "layer[+0] = softmax\n"
           "netconfig = end\n"
           "input_shape = 1,1,784\n"
           "batch_size = 25\n"
           "eta = 0.2\nmomentum = 0.9\nmetric = error\n");
  void *net = CXNNetCreate("cpu", netcfg);
  CHECK(net != NULL, "net for iter leg");
  CHECK(CXNNetInitModel(net) == 0, "InitModel iter leg");
  for (int round = 0; round < 8; ++round) {
    CHECK(CXNNetStartRound(net, round) == 0, "StartRound");
    CHECK(CXNIOBeforeFirst(it) == 0, "BeforeFirst");
    while (CXNIONext(it) == 1)
      CHECK(CXNNetUpdateIter(net, it) == 0, "UpdateIter");
  }
  const char *ev = CXNNetEvaluate(net, it, "train");
  CHECK(ev != NULL, "Evaluate");
  fprintf(stderr, "eval: %s\n", ev);
  double err = atof(strstr(ev, "train-error:") + strlen("train-error:"));
  CHECK(err < 0.2, "iterator-trained net should fit");
  cxn_uint n = 0;
  CHECK(CXNIOBeforeFirst(it) == 0, "BeforeFirst 2");
  CHECK(CXNIONext(it) == 1, "Next 2");
  const cxn_real_t *p = CXNNetPredictIter(net, it, &n);
  CHECK(p != NULL && n == 25, "PredictIter");
  cxn_uint es[2];
  const cxn_real_t *f = CXNNetExtractIter(net, it, "fc1", es);
  CHECK(f != NULL && es[0] == 25 && es[1] == 16, "ExtractIter");
  CXNNetFree(net);
  CXNIOFree(it);
  fprintf(stderr, "C WRAPPER ITERATOR LEG PASSED\n");
  return 0;
}
