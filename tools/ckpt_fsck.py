#!/usr/bin/env python3
"""ckpt_fsck: verify / inspect a cxxnet_tpu model directory offline.

Checks every checkpoint's integrity framing (header magic, CRC32 footer,
length) without building the net or importing jax, reports the training
cursor recorded in each file's state section, and flags stale ``.tmp``
leftovers and quarantined ``.corrupt`` files. Exit status 0 when every
checkpoint verifies, 1 when any is corrupt — wire it into CI or run it
before resuming a long job on a suspect filesystem.

Usage:
    python tools/ckpt_fsck.py <model_dir | file.model> [...]
    python tools/ckpt_fsck.py --deep models/      # also fully parse
    python tools/ckpt_fsck.py --quarantine models/  # move corrupt aside
    python tools/ckpt_fsck.py --selftest          # verify the verifier

Classification per file:
    OK       framed (CXCKHDR1 + CRC32 footer), integrity verified
    LEGACY   footer-less seed/reference-format file — readable but
             unverifiable; rewrite it by resuming + saving once
    CORRUPT  framing present but inconsistent (truncated, torn write,
             bit flip) — the trainer will quarantine it, never load it
"""

from __future__ import annotations

import argparse
import json
import os
import struct
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cxxnet_tpu.utils import checkpoint as ckpt           # noqa: E402
from cxxnet_tpu.utils import serializer                   # noqa: E402


def inspect_file(path: str, deep: bool = False) -> dict:
    """Classify one checkpoint file; returns a report dict."""
    rep = {"path": path, "size": None, "status": "corrupt", "reason": "",
           "net_type": None, "state": None}
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError as e:
        rep["reason"] = "unreadable: %s" % e
        return rep
    rep["size"] = len(blob)
    status, reason, payload = ckpt.verify_blob(blob)
    rep["status"], rep["reason"] = status, reason
    if payload is None:
        return rep
    if len(payload) >= 4:
        (net_type,) = struct.unpack("<i", payload[:4])
        rep["net_type"] = net_type
        if not 0 <= net_type < 1024:
            rep["status"] = "corrupt"
            rep["reason"] = "implausible net_type %d" % net_type
            return rep
    else:
        rep["status"] = "corrupt"
        rep["reason"] = "payload shorter than the net_type header"
        return rep
    st = ckpt.peek_state(payload)
    if st is not None:
        rep["state"] = {k: st[k] for k in
                        ("start_counter", "batches_done", "rng_counter")
                        if k in st}
    if deep and rep["status"] in ("ok", "legacy"):
        # full structural parse (imports jax; catches in-payload damage
        # that CRC can't see on legacy files)
        try:
            from cxxnet_tpu.nnet.trainer import create_net
            r = serializer.Reader(payload)
            net_type = r.read_int32()
            net = create_net(net_type)
            net.set_param("dev", "cpu")
            net.load_model(r)
            net.load_training_state(r)
        except Exception as e:
            rep["status"] = "corrupt"
            rep["reason"] = "deep parse failed: %s" % e
    return rep


def collect(paths):
    """Expand dir args into (checkpoints, stale tmp files, quarantined)."""
    files, tmps, corrupts = [], [], []
    for p in paths:
        if os.path.isdir(p):
            for nm in sorted(os.listdir(p)):
                full = os.path.join(p, nm)
                if nm.endswith(".tmp"):
                    tmps.append(full)
                elif ".corrupt" in nm:
                    corrupts.append(full)
                elif nm.endswith(".model"):
                    files.append(full)
        else:
            files.append(p)
    return files, tmps, corrupts


def selftest() -> int:
    """Prove the verifier flags every injected corruption: valid file ok,
    truncation / bit flip / torn footer corrupt, legacy recognized, stale
    tmp reported."""
    fails = []

    def expect(name, got, want):
        if got != want:
            fails.append("%s: classified %r, expected %r" % (name, got, want))

    with tempfile.TemporaryDirectory() as d:
        w = serializer.Writer()
        w.write_int32(0)
        w.write_string("ckpt_fsck selftest payload")
        w.write_tensor(__import__("numpy").arange(64, dtype="f4"))
        payload = w.getvalue()

        valid = os.path.join(d, "0001.model")
        ckpt.write_checkpoint(valid, payload)
        expect("valid", inspect_file(valid)["status"], "ok")

        blob = open(valid, "rb").read()
        trunc = os.path.join(d, "0002.model")
        open(trunc, "wb").write(blob[: len(blob) // 2])
        expect("truncated", inspect_file(trunc)["status"], "corrupt")

        flip = os.path.join(d, "0003.model")
        fb = bytearray(blob)
        fb[len(fb) // 2] ^= 0x40
        open(flip, "wb").write(bytes(fb))
        expect("bit-flip", inspect_file(flip)["status"], "corrupt")

        torn = os.path.join(d, "0004.model")
        open(torn, "wb").write(blob[:-1])   # footer magic torn off
        expect("torn-footer", inspect_file(torn)["status"], "corrupt")

        legacy = os.path.join(d, "0005.model")
        open(legacy, "wb").write(payload)   # no framing at all
        expect("legacy", inspect_file(legacy)["status"], "legacy")

        stale = os.path.join(d, "0006.model.tmp")
        open(stale, "wb").write(blob[:10])
        _, tmps, _ = collect([d])
        expect("stale-tmp", [os.path.basename(t) for t in tmps],
               ["0006.model.tmp"])

        # the directory checker reflects the injected corruption in rc
        rc = main([d])
        expect("dir-exit-code", rc, 1)

    if fails:
        for f in fails:
            print("SELFTEST FAIL: %s" % f)
        return 1
    print("ckpt_fsck selftest: all corruption classes detected")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*", help="model dirs or files")
    ap.add_argument("--deep", action="store_true",
                    help="fully parse each checkpoint (imports jax)")
    ap.add_argument("--quarantine", action="store_true",
                    help="rename corrupt files to <name>.corrupt")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable report on stdout")
    ap.add_argument("--selftest", action="store_true",
                    help="verify the verifier against injected corruption")
    args = ap.parse_args(argv)
    if args.selftest:
        return selftest()
    if not args.paths:
        ap.error("no model dir or file given")
    files, tmps, corrupts = collect(args.paths)
    reports = [inspect_file(p, deep=args.deep) for p in files]
    n_bad = sum(r["status"] == "corrupt" for r in reports)
    if args.quarantine:
        for r in reports:
            if r["status"] == "corrupt":
                r["quarantined_to"] = ckpt.quarantine(r["path"], r["reason"])
    if args.as_json:
        print(json.dumps({"checkpoints": reports, "stale_tmp": tmps,
                          "quarantined": corrupts}, indent=2))
    else:
        for r in reports:
            st = r["state"] or {}
            cursor = (" round=%s batch=%s" % (st.get("start_counter", "?"),
                                              st.get("batches_done", "?"))
                      if st else "")
            print("%-8s %10s bytes  %s%s%s" %
                  (r["status"].upper(), r["size"], r["path"], cursor,
                   ("  [%s]" % r["reason"]) if r["reason"] else ""))
        for t in tmps:
            print("STALE    %10s bytes  %s  [leftover tmp from a killed "
                  "write]" % (os.path.getsize(t), t))
        for c in corrupts:
            print("QUARANT  %10s bytes  %s" % (os.path.getsize(c), c))
        print("%d checkpoint(s): %d ok, %d legacy, %d corrupt, "
              "%d stale tmp" %
              (len(reports),
               sum(r["status"] == "ok" for r in reports),
               sum(r["status"] == "legacy" for r in reports),
               n_bad, len(tmps)))
    return 1 if n_bad else 0


if __name__ == "__main__":
    sys.exit(main())
