#!/usr/bin/env python
"""Summarize onchip_logs/ (produced by tools/onchip_queue.sh) into a
markdown block for ROUND_NOTES.md: bench lines, the MFU sweep table with
fusion/LRN ablation ratios, pipeline lines, and per-step status.

Usage: python tools/summarize_onchip.py [onchip_logs]
"""

import json
import os
import sys


def read_json_lines(path):
    rows = []
    if not os.path.isfile(path):
        return rows
    for line in open(path):
        line = line.strip()
        if line.startswith("{"):
            try:
                rows.append(json.loads(line))
            except ValueError:
                pass
    return rows


def main():
    d = sys.argv[1] if len(sys.argv) > 1 else "onchip_logs"
    out = []

    status = os.path.join(d, "STATUS")
    if os.path.isfile(status):
        out.append("## Queue status")
        out.append("```")
        out.extend(open(status).read().strip().splitlines())
        out.append("```")

    for name in ("bench", "layout", "poolab", "cross1x1", "pipeline",
                 "benchall"):
        rows = read_json_lines(os.path.join(d, "%s.log" % name))
        if rows:
            out.append("## %s" % name)
            for r in rows:
                out.append("- `%s`" % json.dumps(r))

    mfu = read_json_lines(os.path.join(d, "mfu.log"))
    if mfu:
        out.append("## MFU sweep")
        out.append("| model | batch | dtype | fused | lrn | img/s or tok/s |")
        out.append("|---|---|---|---|---|---|")
        for r in mfu:
            out.append("| %s | %s | %s | %s | %s | %s |" % (
                r.get("model"), r.get("batch"),
                r.get("dtype", "-"), r.get("fused", "-"),
                r.get("lrn", "-"),
                r.get("images_per_sec") or r.get("tokens_per_sec")
                or ("ERR: " + str(r.get("error"))[:60])))
        # ablation ratios
        def find(model, batch, **kw):
            for r in mfu:
                if r.get("model") == model and r.get("batch") == batch \
                        and all(r.get(k) == v for k, v in kw.items()) \
                        and "images_per_sec" in r:
                    return r["images_per_sec"]
            return None
        gf = find("googlenet", 256, fused=1, lrn="default")
        gu = find("googlenet", 256, fused=0)
        if gf and gu:
            out.append("")
            out.append("- sibling-conv fusion: %.2fx on GoogLeNet b256 "
                       "(%.0f vs %.0f img/s)" % (gf / gu, gf, gu))
        ap = find("alexnet", 256, lrn="default", dtype="bf16")
        ax = find("alexnet", 256, lrn="xla")
        if ap and ax:
            out.append("- LRN pallas-vs-xla on AlexNet b256: %.2fx "
                       "(%.0f vs %.0f img/s)" % (ap / ax, ap, ax))

    kern = os.path.join(d, "kernels.log")
    if os.path.isfile(kern):
        tail = open(kern).read().strip().splitlines()[-1:]
        out.append("## kernels: %s" % (tail[0] if tail else "?"))

    mfut = os.path.join(d, "mfutable.log")
    if os.path.isfile(mfut):
        out.append("## MFU table (tools/roofline.py from this run's logs)")
        out.extend(l.rstrip() for l in open(mfut)
                   if l.startswith("|") or l.startswith("#"))

    dect = os.path.join(d, "decodetable.log")
    if os.path.isfile(dect):
        out.append("## Decode bound table (roofline --decode, measured "
                   "vs HBM bound)")
        out.extend(l.rstrip() for l in open(dect)
                   if l.startswith("|") or l.startswith("#"))

    print("\n".join(out))


if __name__ == "__main__":
    main()
