#!/usr/bin/env python3
"""cxxlint: project-native static analysis for tpu-cxxnet.

Generic linters cannot see this project's invariants: which attribute is
a lock, which callable dispatches an async XLA program, which string is a
telemetry series, which comparison reads a conf key. This tool walks the
``cxxnet_tpu`` package's ASTs (stdlib-only, jax-free — it PARSES the
code, never imports it) and enforces four rule families the review
history shows humans keep re-finding by hand (doc/static_analysis.md has
the full catalog with examples):

concurrency
    lock-cycle      cycles in the project lock-acquisition graph (with-
                    statement nesting, including cross-method and cross-
                    module edges through resolvable calls)
    lock-rank       a static graph edge that contradicts the runtime
                    rank table (cxxnet_tpu/utils/lockrank.py RANKS)
    lock-blocking   blocking operations (socket/file IO, sleep,
                    subprocess, Event.wait, queue get/put, jit dispatch)
                    reachable while a lock is held
    thread-unjoined non-daemon threads that are never joined

jax hazards
    donated-reuse   reading an argument after passing it to a
                    donate_argnums call site (the buffer is gone)
    traced-branch   Python truthiness/comparison branching on a traced
                    parameter inside a jit-compiled function
    wallclock       any time.time() call: durations must use
                    time.monotonic()/perf_counter(); genuinely-wall-
                    clock uses carry a suppression comment with a reason
    timed-dispatch  a telemetry.span region that calls an async-
                    dispatching jit program with no block_until_ready —
                    the span times DISPATCH, not compute

conf-key registry
    conf-undocumented  a key the code reads (set_param comparisons,
                       startswith prefixes) that no doc/*.md mentions
    conf-dead          a key documented in a doc key table or config
                       example that nothing in the package reads

metric registry
    metric-name      a telemetry series name with characters outside the
                     project convention [A-Za-z0-9_./]
    metric-type      one series name used as two different metric types
                     (counter vs gauge vs histogram)
    metric-suffix    unit-convention violations (statusd appends _total/
                     _seconds — a raw name carrying them double-suffixes;
                     a literal Prometheus counter must end in _total)
    metric-collision two distinct series names that collide after
                     Prometheus sanitization (both become cxxnet_a_b)
    metric-doc       an exported ``cxxnet_*`` series that appears in no
                     backticked span of doc/observability.md or
                     doc/serving.md (the doc tables ARE the dashboard
                     contract), or a transition-latch event (autopsy
                     TRANSITION_EVENTS) missing a constant set (=1) or
                     clear (=0) record site — a latch nobody clears is
                     a permanent false alarm

Suppression (reason REQUIRED — an empty reason is itself a finding)::

    t_wall = time.time()  # cxxlint: disable=wallclock — flight-record epoch

Baseline / ratchet: ``tools/cxxlint_baseline.json`` grandfathers existing
violations as fingerprint->count. The count may only SHRINK: a finding
not covered by the baseline fails (new violation), and a baseline entry
no longer matched fails (stale — delete it, the debt is paid). Update
with ``--update-baseline`` only when deliberately accepting debt.

Usage:
    python tools/cxxlint.py                 # lint the package (make lint)
    python tools/cxxlint.py --lock-graph    # print the acquisition graph
    python tools/cxxlint.py --selftest      # parse-all + clean-tree gate
"""

from __future__ import annotations

import ast
import json
import os
import re
import sys
import time
from collections import defaultdict
from typing import Dict, List, Optional, Set, Tuple

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = "cxxnet_tpu"
BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "cxxlint_baseline.json")

RULES = {
    "lock-cycle": "cycle in the lock-acquisition graph",
    "lock-rank": "static lock-graph edge contradicts lockrank.RANKS",
    "lock-blocking": "blocking operation reachable while a lock is held",
    "thread-unjoined": "non-daemon thread is never joined",
    "donated-reuse": "argument read after a donate_argnums call consumed it",
    "traced-branch": "Python branch on a traced value inside a jit function",
    "wallclock": "time.time() call (durations need monotonic time)",
    "timed-dispatch": "span times an async jit dispatch with no sync",
    "conf-undocumented": "code reads a conf key no doc/*.md mentions",
    "conf-dead": "doc registers a conf key nothing reads",
    "err-vocab": "servd/routerd ERR string missing from serving.md's "
                 "error-vocabulary table",
    "metric-name": "telemetry series name outside [A-Za-z0-9_./]",
    "metric-type": "one series name used as two metric types",
    "metric-suffix": "metric unit-suffix convention violation",
    "metric-collision": "two series names collide after sanitization",
    "metric-doc": "exported cxxnet_* series missing from the doc metric "
                  "tables, or latch event without set+clear sites",
    "bad-suppression": "cxxlint disable comment without a reason",
}

HINTS = {
    "lock-cycle": "break the cycle: release before calling, or reorder "
                  "per lockrank.RANKS",
    "lock-rank": "renumber lockrank.RANKS to a topological order of "
                 "`cxxlint.py --lock-graph`",
    "lock-blocking": "copy state under the lock, do the slow work after "
                     "release (see telemetry.flush)",
    "thread-unjoined": "pass daemon=True or join() it on shutdown",
    "donated-reuse": "rebind the result or copy before the call; the "
                     "donated buffer no longer exists",
    "traced-branch": "use jnp.where/lax.cond, or branch on static "
                     "Python config captured by the closure",
    "wallclock": "time.monotonic() for durations; if wall-clock is the "
                 "point, add `# cxxlint: disable=wallclock — <why>`",
    "timed-dispatch": "jax.block_until_ready(out) inside the span, or "
                      "suppress with a reason if dispatch-time is meant",
    "conf-undocumented": "document the key in the owning doc/*.md page",
    "conf-dead": "delete the doc row or wire the key back up",
    "err-vocab": "add a row to doc/serving.md '### Error vocabulary' — "
                 "the table IS the wire contract the router dispatches "
                 "on",
    "metric-name": "stick to letters, digits, '_', '.', '/'",
    "metric-type": "pick one type per name; split the series otherwise",
    "metric-suffix": "statusd appends _total/_seconds — drop the unit "
                     "suffix from the raw name",
    "metric-collision": "rename one series; both sanitize to the same "
                        "Prometheus name",
    "metric-doc": "add a backticked row to doc/observability.md (or "
                  "serving.md); a latch event needs literal =1 set and "
                  "=0 clear record sites",
    "bad-suppression": "a suppression must say WHY: "
                       "`# cxxlint: disable=<rule> — <reason>`",
}

SUPPRESS_RE = re.compile(
    r"#\s*cxxlint:\s*disable=([A-Za-z,-]+)\s*(?:(?:—|--|-)\s*(.*))?")

# blocking primitives by dotted-name suffix (resolution-free tier)
BLOCKING_SUFFIX = {
    "time.sleep": "time.sleep",
    "os.system": "os.system",
    "subprocess.run": "subprocess", "subprocess.call": "subprocess",
    "subprocess.check_call": "subprocess",
    "subprocess.check_output": "subprocess",
    "subprocess.Popen": "subprocess",
    "socket.create_connection": "socket connect",
    "socket.create_server": "socket bind",
    "select.select": "select.select",
}
# blocking method names on ANY receiver (socket-shaped verbs rare enough
# elsewhere to be safe)
BLOCKING_METHODS = {"accept": "socket accept", "recv": "socket recv",
                    "recvfrom": "socket recv", "sendall": "socket send",
                    "connect": "socket connect"}
# result-sync markers only: jnp.asarray on an INPUT is not a sync, so
# asarray deliberately does not count
SYNC_MARKERS = {"block_until_ready", "device_get", "process_allgather"}
METRIC_FUNCS = {"count": "counter", "gauge": "gauge", "hist": "histogram",
                "declare_hist": "histogram", "span": "histogram",
                "span_event": "histogram"}
METRIC_NAME_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_./]*$")
PROM_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
IDENT_RE = re.compile(r"^[a-z_][a-z0-9_]*$")


class Finding:
    __slots__ = ("rule", "path", "line", "msg", "key")

    def __init__(self, rule: str, path: str, line: int, msg: str,
                 key: Optional[str] = None):
        self.rule = rule
        self.path = path
        self.line = int(line)
        self.msg = msg
        self.key = key if key is not None else msg

    def fingerprint(self, root: str) -> str:
        rel = os.path.relpath(self.path, root)
        return "%s|%s|%s" % (self.rule, rel.replace(os.sep, "/"), self.key)

    def render(self, root: str) -> str:
        rel = os.path.relpath(self.path, root)
        return "%s:%d: [%s] %s\n    hint: %s" % (
            rel, self.line, self.rule, self.msg, HINTS.get(self.rule, ""))


def dotted(node) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return None if base is None else base + "." + node.attr
    return None


def const_str(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


# ----------------------------------------------------------------------
# project model: what the ASTs tell us about classes, locks and types
# ----------------------------------------------------------------------

LOCK_CTORS = {"threading.Lock", "threading.RLock", "threading.Condition"}
KIND_CTORS = {"threading.Event": ("event",), "queue.Queue": ("queue",),
              "threading.Thread": ("thread",), "open": ("file",)}


class ClassInfo:
    def __init__(self, modkey: str, name: str, node: ast.ClassDef):
        self.modkey = modkey
        self.name = name
        self.node = node
        self.methods: Dict[str, ast.FunctionDef] = {}
        self.attr_kinds: Dict[str, tuple] = {}   # attr -> kind tuple


class ModuleInfo:
    def __init__(self, key: str, path: str, tree: ast.Module, src: str):
        self.key = key
        self.path = path
        self.tree = tree
        self.src = src
        self.lines = src.splitlines()
        self.nodes = list(ast.walk(tree))   # walked once, reused by
        #                                     every whole-module rule
        self.classes: Dict[str, ClassInfo] = {}
        self.functions: Dict[str, ast.FunctionDef] = {}
        self.mod_aliases: Dict[str, str] = {}     # alias -> module key
        self.name_imports: Dict[str, Tuple[str, str]] = {}
        self.var_kinds: Dict[str, tuple] = {}
        self.suppress: Dict[int, Tuple[Set[str], str]] = {}


class FuncEntry:
    def __init__(self, modkey: str, qual: str, node, cls=None):
        self.modkey = modkey
        self.qual = qual
        self.node = node
        self.cls: Optional[ClassInfo] = cls
        self.key = (modkey, qual)
        self.calls: List[Tuple[tuple, int]] = []       # (callee key, line)
        self.locks: List[Tuple[str, int]] = []         # direct acquisitions
        self.blocking: List[Tuple[str, int]] = []      # context-filtered
        self.lock_edges: List[Tuple[str, str, int]] = []
        self.lock_calls: List[Tuple[str, tuple, int]] = []
        self.lock_dispatch: List[Tuple[str, int]] = []  # jit under lock
        self.local_defs: Dict[str, tuple] = {}
        # (varname, resolved callee) for assignments that BECOME jit
        # vars iff the callee turns out to be a jit source — the only
        # part of the analysis the second pass can change
        self.maybe_jit_assigns: List[Tuple[str, tuple]] = []
        # own-scope nodes (nested def/class bodies excluded — they are
        # their own FuncEntry), walked once at registration
        self.own_nodes: List[ast.AST] = list(_walk_no_nested(node))


class Project:
    def __init__(self, root: str, pkg: str = PKG):
        self.root = root
        self.pkg_dir = os.path.join(root, pkg)
        self.modules: Dict[str, ModuleInfo] = {}
        self.funcs: Dict[tuple, FuncEntry] = {}
        self.attr_locks: Dict[str, Set[str]] = defaultdict(set)
        self.jit_sources: Set[tuple] = set()
        self.parse_errors: List[str] = []
        self._load()
        self._index()

    # -- loading -------------------------------------------------------
    def _load(self) -> None:
        for dirpath, dirnames, filenames in os.walk(self.pkg_dir):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, self.pkg_dir)
                key = rel[:-3].replace(os.sep, ".")
                if key.endswith(".__init__"):
                    key = key[:-len(".__init__")]
                elif key == "__init__":
                    key = ""
                with open(path, "r", encoding="utf-8") as f:
                    src = f.read()
                try:
                    tree = ast.parse(src, filename=path)
                except SyntaxError as e:
                    self.parse_errors.append("%s: %s" % (path, e))
                    continue
                self.modules[key] = ModuleInfo(key, path, tree, src)

    # -- indexing ------------------------------------------------------
    def _index(self) -> None:
        for mod in self.modules.values():
            self._index_imports(mod)
            self._index_suppressions(mod)
            for node in mod.tree.body:
                if isinstance(node, ast.ClassDef):
                    ci = ClassInfo(mod.key, node.name, node)
                    mod.classes[node.name] = ci
                    for sub in node.body:
                        if isinstance(sub, (ast.FunctionDef,
                                            ast.AsyncFunctionDef)):
                            ci.methods[sub.name] = sub
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    mod.functions[node.name] = node
        # attr kinds need classes of ALL modules resolvable first
        for mod in self.modules.values():
            for ci in mod.classes.values():
                for meth in ci.methods.values():
                    self._collect_attr_kinds(mod, ci, meth)
            for node in mod.tree.body:
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    kind = self.infer_kind(node.value, mod,
                                           "%s.%s" % (mod.key,
                                                      node.targets[0].id))
                    if kind is not None:
                        mod.var_kinds[node.targets[0].id] = kind
        for mod in self.modules.values():
            for ci in mod.classes.values():
                for attr, kind in ci.attr_kinds.items():
                    if kind[0] == "lock":
                        self.attr_locks[attr].add(kind[1])
        # function registry (nested defs included, qualified)
        for mod in self.modules.values():
            for name, node in mod.functions.items():
                self._register_func(mod, name, node, None)
            for ci in mod.classes.values():
                for mname, meth in ci.methods.items():
                    self._register_func(mod, "%s.%s" % (ci.name, mname),
                                        meth, ci)

    def _register_func(self, mod, qual, node, cls) -> None:
        fe = FuncEntry(mod.key, qual, node, cls)
        self.funcs[fe.key] = fe
        for sub in ast.walk(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and sub is not node:
                subqual = "%s.%s" % (qual, sub.name)
                if (mod.key, subqual) not in self.funcs:
                    sube = FuncEntry(mod.key, subqual, sub, cls)
                    self.funcs[sube.key] = sube
                fe.local_defs[sub.name] = (mod.key, subqual)

    def _index_imports(self, mod: ModuleInfo) -> None:
        parts = mod.key.split(".") if mod.key else []
        for node in mod.nodes:
            if isinstance(node, ast.Import):
                for a in node.names:
                    name = a.name
                    if name == PKG:
                        continue
                    if name.startswith(PKG + "."):
                        key = name[len(PKG) + 1:]
                        if key in self.modules:
                            mod.mod_aliases[a.asname
                                            or name.split(".")[-1]] = key
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = parts[:-node.level] if node.level <= len(parts) \
                        else []
                    tgt = base + (node.module.split(".")
                                  if node.module else [])
                else:
                    if not node.module:
                        continue
                    if node.module == PKG:
                        tgt = []
                    elif node.module.startswith(PKG + "."):
                        tgt = node.module[len(PKG) + 1:].split(".")
                    else:
                        continue
                tkey = ".".join(tgt)
                for a in node.names:
                    sub = ".".join(tgt + [a.name])
                    if sub in self.modules:
                        mod.mod_aliases[a.asname or a.name] = sub
                    elif tkey in self.modules:
                        mod.name_imports[a.asname or a.name] = (tkey,
                                                                a.name)

    def _index_suppressions(self, mod: ModuleInfo) -> None:
        """A suppression covers its own line; on a comment-only line the
        reason may continue over following comment lines and the whole
        block covers the first CODE line after it."""
        for i, line in enumerate(mod.lines, 1):
            m = SUPPRESS_RE.search(line)
            if m is None:
                continue
            rules = {r.strip() for r in m.group(1).split(",")
                     if r.strip()}
            reason = (m.group(2) or "").strip()
            mod.suppress[i] = (rules, reason)
            if not line.strip().startswith("#"):
                continue
            j = i
            while j < len(mod.lines) \
                    and mod.lines[j].strip().startswith("#"):
                j += 1
            if j < len(mod.lines) and mod.lines[j].strip() \
                    and j + 1 not in mod.suppress:
                mod.suppress[j + 1] = (rules, reason)

    # -- kind inference ------------------------------------------------
    def infer_kind(self, value, mod: ModuleInfo,
                   autoname: str) -> Optional[tuple]:
        """What does this r-value construct? -> ("lock", name) |
        ("class", modkey, clsname) | ("event"|"queue"|"thread"|"file",)"""
        if not isinstance(value, ast.Call):
            return None
        d = dotted(value.func)
        if d is None:
            return None
        if d in LOCK_CTORS or d in ("Lock", "RLock", "Condition"):
            return ("lock", autoname)
        if d.endswith("lockrank.lock") or d.endswith("lockrank.condition") \
                or (d in ("lock", "condition")
                    and "lockrank" in mod.name_imports.get(d, ("",))[0]):
            nm = const_str(value.args[0]) if value.args else None
            return ("lock", nm or autoname)
        if d in KIND_CTORS:
            return KIND_CTORS[d]
        if d in ("Event", "Queue", "Thread"):
            return {"Event": ("event",), "Queue": ("queue",),
                    "Thread": ("thread",)}[d]
        cls = self.resolve_class_name(d, mod)
        if cls is not None:
            return ("class",) + cls
        return None

    def resolve_class_name(self, d: str, mod: ModuleInfo) \
            -> Optional[Tuple[str, str]]:
        if "." in d:
            head, _, tail = d.partition(".")
            tmod = mod.mod_aliases.get(head)
            if tmod is not None and "." not in tail:
                tm = self.modules.get(tmod)
                if tm is not None and tail in tm.classes:
                    return (tmod, tail)
            return None
        if d in mod.classes:
            return (mod.key, d)
        imp = mod.name_imports.get(d)
        if imp is not None:
            tm = self.modules.get(imp[0])
            if tm is not None and imp[1] in tm.classes:
                return imp
        return None

    def _collect_attr_kinds(self, mod, ci: ClassInfo, meth) -> None:
        for node in ast.walk(meth):
            tgt = None
            value = None
            ann = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt, value = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign):
                tgt, value, ann = node.target, node.value, node.annotation
            if not (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"):
                continue
            attr = tgt.attr
            kind = None
            if value is not None:
                kind = self.infer_kind(
                    value, mod, "%s.%s.%s" % (mod.key, ci.name, attr))
            if kind is None and ann is not None:
                kind = self.ann_kind(ann, mod)
            if kind is not None and (attr not in ci.attr_kinds
                                     or ci.attr_kinds[attr][0] != "lock"):
                ci.attr_kinds[attr] = kind

    def ann_kind(self, ann, mod: ModuleInfo) -> Optional[tuple]:
        """Kind from a type annotation (Optional[X] unwrapped)."""
        names = [dotted(n) or getattr(n, "id", "")
                 for n in ast.walk(ann)
                 if isinstance(n, (ast.Name, ast.Attribute))]
        s = const_str(ann)
        if s:
            names.append(s)
        for n in names:
            if not n:
                continue
            tail = n.split(".")[-1].strip("'\"")
            if tail == "Thread":
                return ("thread",)
            if tail == "Event":
                return ("event",)
            if tail == "Queue":
                return ("queue",)
            cls = self.resolve_class_name(tail, mod)
            if cls is not None:
                return ("class",) + cls
        return None



# ----------------------------------------------------------------------
# per-function analysis: locks, blocking ops, calls, span blocks
# ----------------------------------------------------------------------

def _walk_no_nested(node):
    """ast.walk that does not descend into nested function/class defs
    (they are analyzed as their own FuncEntry)."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                continue
            stack.append(child)


class _FuncAnalysis:
    def __init__(self, project: Project, fe: FuncEntry,
                 jit_sources: Set[tuple]):
        self.p = project
        self.fe = fe
        self.mod = project.modules[fe.modkey]
        self.jit_sources = jit_sources
        self.env: Dict[str, tuple] = {}
        self.jit_vars: Set[str] = set()
        fe.calls, fe.locks, fe.blocking = [], [], []
        fe.lock_edges, fe.lock_calls, fe.lock_dispatch = [], [], []
        fe.block_hits: List[Tuple[str, str, int]] = []
        fe.span_blocks: List[Tuple[int, bool, bool]] = []
        self._prepass()
        self._visit_block(fe.node.body, [])

    # -- environment ---------------------------------------------------
    def _prepass(self) -> None:
        a = self.fe.node.args
        for arg in (list(getattr(a, "posonlyargs", [])) + list(a.args)
                    + list(a.kwonlyargs)):
            if arg.annotation is not None:
                k = self.p.ann_kind(arg.annotation, self.mod)
                if k is not None:
                    self.env[arg.arg] = k
        self.fe.maybe_jit_assigns = []
        for node in self.fe.own_nodes:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                tname = node.targets[0].id
                kind = self.p.infer_kind(
                    node.value, self.mod,
                    "%s.%s.%s" % (self.fe.modkey, self.fe.qual, tname))
                if kind is not None:
                    self.env[tname] = kind
                if isinstance(node.value, ast.Call) \
                        and self._is_jit_maker(node.value, tname):
                    self.jit_vars.add(tname)

    def _is_jit_maker(self, call: ast.Call, tname: str) -> bool:
        d = dotted(call.func) or ""
        if d.endswith("jax.jit") or "jit_watch" in d \
                or "_watched_jit" in d:
            return True
        key = self._resolve_call(call.func)
        if key is None:
            return False
        self.fe.maybe_jit_assigns.append((tname, key))
        return key in self.jit_sources

    # -- resolution ----------------------------------------------------
    def _recv_kind(self, expr) -> Optional[tuple]:
        """Kind of a receiver expression (Name / self.attr / var.attr)."""
        if isinstance(expr, ast.Name):
            k = self.env.get(expr.id) or self.mod.var_kinds.get(expr.id)
            return k
        if isinstance(expr, ast.Attribute):
            base = expr.value
            ci = None
            if isinstance(base, ast.Name) and base.id == "self" \
                    and self.fe.cls is not None:
                ci = self.fe.cls
            else:
                bk = self._recv_kind(base)
                if bk is not None and bk[0] == "class":
                    tm = self.p.modules.get(bk[1])
                    ci = tm.classes.get(bk[2]) if tm else None
            if ci is not None and expr.attr in ci.attr_kinds:
                return ci.attr_kinds[expr.attr]
        return None

    def _resolve_lock(self, expr) -> Optional[str]:
        k = self._recv_kind(expr)
        if k is not None and k[0] == "lock":
            return k[1]
        # fallback: a lock attribute name unique across the project
        if isinstance(expr, ast.Attribute):
            names = self.p.attr_locks.get(expr.attr)
            if names and len(names) == 1:
                return next(iter(names))
        return None

    def _resolve_call(self, func) -> Optional[tuple]:
        if isinstance(func, ast.Name):
            n = func.id
            if n in self.fe.local_defs:
                return self.fe.local_defs[n]
            if n in self.mod.functions:
                return (self.mod.key, n)
            imp = self.mod.name_imports.get(n)
            if imp is not None:
                tm = self.p.modules.get(imp[0])
                if tm is not None:
                    if imp[1] in tm.functions:
                        return (imp[0], imp[1])
                    if imp[1] in tm.classes \
                            and "__init__" in tm.classes[imp[1]].methods:
                        return (imp[0], imp[1] + ".__init__")
            if n in self.mod.classes \
                    and "__init__" in self.mod.classes[n].methods:
                return (self.mod.key, n + ".__init__")
            return None
        if isinstance(func, ast.Attribute):
            base = func.value
            if isinstance(base, ast.Name) and base.id == "self" \
                    and self.fe.cls is not None \
                    and func.attr in self.fe.cls.methods:
                return (self.fe.modkey,
                        "%s.%s" % (self.fe.cls.name, func.attr))
            if isinstance(base, ast.Name) \
                    and base.id in self.mod.mod_aliases:
                tmk = self.mod.mod_aliases[base.id]
                tm = self.p.modules.get(tmk)
                if tm is not None:
                    if func.attr in tm.functions:
                        return (tmk, func.attr)
                    if func.attr in tm.classes \
                            and "__init__" in tm.classes[func.attr].methods:
                        return (tmk, func.attr + ".__init__")
                return None
            bk = self._recv_kind(base)
            if bk is not None and bk[0] == "class":
                tm = self.p.modules.get(bk[1])
                ci = tm.classes.get(bk[2]) if tm else None
                if ci is not None and func.attr in ci.methods:
                    return (bk[1], "%s.%s" % (bk[2], func.attr))
        return None

    # -- blocking classification ---------------------------------------
    def _blocking_desc(self, call: ast.Call,
                       held: List[Tuple[str, int]]) -> Optional[str]:
        func = call.func
        d = dotted(func) or ""
        for suf, desc in BLOCKING_SUFFIX.items():
            if d == suf or d.endswith("." + suf):
                return desc
        if d == "open" or d.endswith(".open"):
            return "file open"
        if isinstance(func, ast.Name) and func.id in self.jit_vars:
            return "jit dispatch"
        if isinstance(func, ast.Call) \
                and (dotted(func.func) or "").endswith("jax.jit"):
            return "jit dispatch"
        if not isinstance(func, ast.Attribute):
            return None
        attr = func.attr
        if attr in BLOCKING_METHODS:
            return BLOCKING_METHODS[attr]
        recv = func.value
        if attr == "wait":
            lock = self._resolve_lock(recv)
            if lock is not None:
                return None     # Condition.wait releases its own lock
            k = self._recv_kind(recv)
            if k is not None and k[0] == "event":
                return "Event.wait"
            return None
        k = self._recv_kind(recv)
        if k is None:
            return None
        if k[0] == "thread" and attr == "join":
            return "Thread.join"
        if k[0] == "queue" and attr in ("get", "put", "join"):
            return "queue.%s" % attr
        if k[0] == "file" and attr in ("read", "readline", "readlines",
                                       "write", "writelines", "flush"):
            return "file IO"
        return None

    # -- statement walk ------------------------------------------------
    def _scan_calls(self, expr, held: List[Tuple[str, int]]) -> None:
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            line = getattr(node, "lineno", 0)
            key = self._resolve_call(node.func)
            if key is not None:
                self.fe.calls.append((key, line))
                if held:
                    self.fe.lock_calls.append((held[-1][0], key, line))
            desc = self._blocking_desc(node, held)
            if desc is not None:
                self.fe.blocking.append((desc, line))
                if held:
                    self.fe.block_hits.append((held[-1][0], desc, line))
                if desc == "jit dispatch" and held:
                    self.fe.lock_dispatch.append((held[-1][0], line))

    def _is_span_call(self, expr) -> bool:
        if not isinstance(expr, ast.Call):
            return False
        d = dotted(expr.func) or ""
        return d == "span" or d.endswith(".span")

    def _analyze_span(self, w: ast.With) -> None:
        has_dispatch = has_sync = False
        for st in w.body:
            for node in ast.walk(st):
                if isinstance(node, ast.Call):
                    if isinstance(node.func, ast.Name) \
                            and node.func.id in self.jit_vars:
                        has_dispatch = True
                    elif isinstance(node.func, ast.Call) and (dotted(
                            node.func.func) or "").endswith("jax.jit"):
                        has_dispatch = True
                if isinstance(node, ast.Attribute) \
                        and node.attr in SYNC_MARKERS:
                    has_sync = True
                if isinstance(node, ast.Name) and node.id in SYNC_MARKERS:
                    has_sync = True
        self.fe.span_blocks.append((w.lineno, has_dispatch, has_sync))

    def _visit_block(self, stmts, held: List[Tuple[str, int]]) -> None:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            if isinstance(st, ast.With):
                new_held = list(held)
                is_span = False
                for item in st.items:
                    self._scan_calls(item.context_expr, held)
                    if self._is_span_call(item.context_expr):
                        is_span = True
                        continue
                    ln = self._resolve_lock(item.context_expr)
                    if ln is not None:
                        if new_held:
                            self.fe.lock_edges.append(
                                (new_held[-1][0], ln, st.lineno))
                        self.fe.locks.append((ln, st.lineno))
                        new_held.append((ln, st.lineno))
                if is_span:
                    self._analyze_span(st)
                self._visit_block(st.body, new_held)
                continue
            # expressions of this statement (not sub-blocks)
            for field in ("value", "test", "iter", "exc", "msg"):
                sub = getattr(st, field, None)
                if sub is not None and isinstance(sub, ast.AST):
                    self._scan_calls(sub, held)
            if isinstance(st, ast.Return) and st.value is not None:
                pass  # covered by "value"
            for blk in ("body", "orelse", "finalbody"):
                sub = getattr(st, blk, None)
                if isinstance(sub, list) and sub and \
                        isinstance(sub[0], ast.stmt):
                    self._visit_block(sub, held)
            if isinstance(st, ast.Try):
                for h in st.handlers:
                    self._visit_block(h.body, held)


def analyze_all(project: Project) -> None:
    """Two passes: resolve the call graph first, derive the jit-source
    set from it, then re-run with jit knowledge wired in."""
    for fe in project.funcs.values():
        _FuncAnalysis(project, fe, frozenset())
    direct: Set[tuple] = set()
    for fe in project.funcs.values():
        for node in ast.walk(fe.node):
            if isinstance(node, ast.Call):
                d = dotted(node.func) or ""
                if d.endswith("jax.jit") or "jit_watch" in d \
                        or "_watched_jit" in d:
                    direct.add(fe.key)
                    break
    srcs = set(direct)
    changed = True
    while changed:
        changed = False
        for fe in project.funcs.values():
            if fe.key in srcs:
                continue
            if any(ck in srcs for ck, _ in fe.calls):
                srcs.add(fe.key)
                changed = True
    project.jit_sources = srcs
    # second pass only where jit knowledge can change the outcome: a
    # function whose assignments resolve into the jit-source set gains
    # jit vars; everything else keeps its (identical) first-pass result
    for fe in project.funcs.values():
        if any(k in srcs for _, k in fe.maybe_jit_assigns):
            _FuncAnalysis(project, fe, srcs)


def _closure(project: Project, direct_of) -> Dict[tuple, dict]:
    """Fixpoint transitive closure over the resolved call graph.
    ``direct_of(fe) -> {item: site}``; result maps func key ->
    {item: representative site}."""
    sets: Dict[tuple, dict] = {
        fe.key: dict(direct_of(fe)) for fe in project.funcs.values()}
    changed = True
    while changed:
        changed = False
        for fe in project.funcs.values():
            mine = sets[fe.key]
            for ck, line in fe.calls:
                other = sets.get(ck)
                if not other:
                    continue
                for item, site in other.items():
                    if item not in mine:
                        mine[item] = site
                        changed = True
    return sets


# ----------------------------------------------------------------------
# rule drivers
# ----------------------------------------------------------------------

def lock_analysis(project: Project):
    """-> (edges {(src,dst): (relpath,line)}, findings)."""
    locks_of = _closure(
        project, lambda fe: {ln: (fe.modkey, line)
                             for ln, line in fe.locks})
    blocking_of = _closure(
        project, lambda fe: {desc: (fe.modkey, line)
                             for desc, line in fe.blocking})
    edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
    findings: List[Finding] = []

    def add_edge(src, dst, path, line):
        if (src, dst) not in edges:
            edges[(src, dst)] = (path, line)

    for fe in project.funcs.values():
        path = project.modules[fe.modkey].path
        for src, dst, line in fe.lock_edges:
            add_edge(src, dst, path, line)
        for lockname, callee, line in fe.lock_calls:
            for ln in locks_of.get(callee, {}):
                add_edge(lockname, ln, path, line)
        seen_block = set()
        for lockname, desc, line in fe.block_hits:
            key = (lockname, desc, line)
            if key not in seen_block:
                seen_block.add(key)
                findings.append(Finding(
                    "lock-blocking", path, line,
                    "%s while holding %r" % (desc, lockname),
                    key="%s|%s" % (lockname, desc)))
        for lockname, callee, line in fe.lock_calls:
            for desc, origin in blocking_of.get(callee, {}).items():
                key = (lockname, desc, callee)
                if key in seen_block:
                    continue
                seen_block.add(key)
                findings.append(Finding(
                    "lock-blocking", path, line,
                    "call into %s.%s reaches %s (at %s:%d) while "
                    "holding %r" % (callee[0], callee[1], desc,
                                    origin[0], origin[1], lockname),
                    key="%s|%s|%s.%s" % (lockname, desc, callee[0],
                                         callee[1])))
    # cycles (self-edges included: with L held, re-acquiring L deadlocks)
    adj: Dict[str, List[str]] = defaultdict(list)
    for (src, dst) in edges:
        adj[src].append(dst)
    color: Dict[str, int] = {}
    stack: List[str] = []
    cycles: List[List[str]] = []

    def dfs(n):
        color[n] = 1
        stack.append(n)
        for m in adj.get(n, ()):
            if m == n:
                cycles.append([n])
            elif color.get(m, 0) == 0:
                dfs(m)
            elif color.get(m) == 1:
                cyc = stack[stack.index(m):]
                if sorted(cyc) not in [sorted(c) for c in cycles]:
                    cycles.append(list(cyc))
        stack.pop()
        color[n] = 2

    for n in sorted(adj):
        if color.get(n, 0) == 0:
            dfs(n)
    for cyc in cycles:
        segs = []
        site = None
        ring = cyc + [cyc[0]]
        for a, b in zip(ring, ring[1:]):
            e = edges.get((a, b))
            if e is not None:
                segs.append("%s->%s (%s:%d)"
                            % (a, b, os.path.basename(e[0]), e[1]))
                site = site or e
        findings.append(Finding(
            "lock-cycle", site[0] if site else project.pkg_dir,
            site[1] if site else 0,
            "lock-acquisition cycle: " + "  ".join(segs),
            key="|".join(sorted(set(cyc)))))
    # rank consistency with the runtime table
    ranks = parse_ranks(project)
    for (src, dst), (path, line) in sorted(edges.items()):
        if src in ranks and dst in ranks and ranks[src] >= ranks[dst]:
            findings.append(Finding(
                "lock-rank", path, line,
                "edge %s -> %s contradicts lockrank.RANKS "
                "(%d >= %d): the runtime checker would raise here"
                % (src, dst, ranks[src], ranks[dst]),
                key="%s|%s" % (src, dst)))
    return edges, findings


def parse_ranks(project: Project) -> Dict[str, int]:
    mod = project.modules.get("utils.lockrank")
    if mod is None:
        return {}
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "RANKS" \
                and isinstance(node.value, ast.Dict):
            out = {}
            for k, v in zip(node.value.keys, node.value.values):
                ks, vi = const_str(k), getattr(v, "value", None)
                if ks is not None and isinstance(vi, int):
                    out[ks] = vi
            return out
    return {}


def thread_findings(project: Project) -> List[Finding]:
    out = []
    for mod in project.modules.values():
        if "Thread" not in mod.src:
            continue
        assigned = {}
        # one walk: ast.walk yields a parent Assign before its value
        # Call, so the name is always recorded by the time the Thread
        # constructor comes up
        for node in mod.nodes:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.value, ast.Call):
                t = node.targets[0]
                nm = t.id if isinstance(t, ast.Name) else (
                    t.attr if isinstance(t, ast.Attribute) else None)
                if nm:
                    assigned[id(node.value)] = nm
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func) or ""
            if not (d == "Thread" or d.endswith("threading.Thread")):
                continue
            daemon = None
            for kw in node.keywords:
                if kw.arg == "daemon":
                    daemon = getattr(kw.value, "value", None)
            if daemon is True:
                continue
            nm = assigned.get(id(node))
            # left boundary required: client.join(",") must not count
            # as joining a thread named t
            if nm is not None and re.search(
                    r"(?<![A-Za-z0-9_.])" + re.escape(nm)
                    + r"\s*\.\s*join\s*\(", mod.src):
                continue
            out.append(Finding(
                "thread-unjoined", mod.path, node.lineno,
                "thread %s is not daemon=True and never joined"
                % (repr(nm) if nm else "(unnamed)"),
                key=nm or "anon:%d" % node.lineno))
    return out


def wallclock_findings(project: Project) -> List[Finding]:
    out = []
    for mod in project.modules.values():
        for node in mod.nodes:
            if isinstance(node, ast.Call) \
                    and (dotted(node.func) or "") == "time.time":
                line = mod.lines[node.lineno - 1].strip() \
                    if node.lineno <= len(mod.lines) else ""
                out.append(Finding(
                    "wallclock", mod.path, node.lineno,
                    "time.time() — wall clock; durations need "
                    "time.monotonic()", key=line))
    return out


def _donate_idxs(call: ast.Call) -> Optional[Set[int]]:
    if not (dotted(call.func) or "").endswith("jax.jit"):
        return None
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return {v.value}
        if isinstance(v, (ast.Tuple, ast.List)):
            out = set()
            for e in v.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value,
                                                              int):
                    out.add(e.value)
            return out
    return None


def donated_reuse_findings(project: Project) -> List[Finding]:
    out = []
    for fe in project.funcs.values():
        mod = project.modules[fe.modkey]
        if "donate_argnums" not in mod.src:
            continue    # _donate_idxs needs the literal kwarg
        donating: Dict[str, Set[int]] = {}
        stores: Dict[str, List[int]] = defaultdict(list)
        loads: Dict[str, List[int]] = defaultdict(list)
        all_calls: List[ast.Call] = []
        for node in fe.own_nodes:
            if isinstance(node, ast.Name):
                (stores if isinstance(node.ctx, ast.Store)
                 else loads)[node.id].append(node.lineno)
            elif isinstance(node, ast.Call):
                all_calls.append(node)
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call):
                cands = [node.value] + [a for a in node.value.args
                                        if isinstance(a, ast.Call)]
                for c in cands:
                    idxs = _donate_idxs(c)
                    if idxs:
                        donating[node.targets[0].id] = idxs
        if not donating and not any(isinstance(c.func, ast.Call)
                                    for c in all_calls):
            continue
        calls: List[Tuple[int, List[str]]] = []
        for node in all_calls:
            idxs = None
            if isinstance(node.func, ast.Name) \
                    and node.func.id in donating:
                idxs = donating[node.func.id]
            elif isinstance(node.func, ast.Call):
                idxs = _donate_idxs(node.func)
            if not idxs:
                continue
            names = [a.id for i, a in enumerate(node.args)
                     if i in idxs and isinstance(a, ast.Name)]
            if names:
                calls.append((node.lineno, names))
        for callline, names in calls:
            for nm in names:
                later = [ln for ln in loads[nm] if ln > callline]
                if not later:
                    continue
                use = min(later)
                if any(callline <= s <= use for s in stores[nm]):
                    continue
                out.append(Finding(
                    "donated-reuse", mod.path, use,
                    "%r donated to the jit call at line %d is read "
                    "again — the buffer was consumed" % (nm, callline),
                    key="%s:%d" % (nm, callline)))
    return out


def _jit_roots(project: Project) -> Set[tuple]:
    roots: Set[tuple] = set()
    for fe in project.funcs.values():
        for dec in getattr(fe.node, "decorator_list", []):
            d = dotted(dec) or dotted(getattr(dec, "func", None)) or ""
            if "jit" in d:
                roots.add(fe.key)
        for node in fe.own_nodes:
            if isinstance(node, ast.Call) \
                    and (dotted(node.func) or "").endswith("jax.jit") \
                    and node.args \
                    and isinstance(node.args[0], ast.Name) \
                    and node.args[0].id in fe.local_defs:
                roots.add(fe.local_defs[node.args[0].id])
        if re.match(r"^_make_.*_step$", fe.qual.split(".")[-1]):
            roots.update(fe.local_defs.values())
    return roots


def _traced_names(test, params: Set[str]) -> List[str]:
    if isinstance(test, ast.Name):
        return [test.id] if test.id in params else []
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _traced_names(test.operand, params)
    if isinstance(test, ast.BoolOp):
        out = []
        for v in test.values:
            out.extend(_traced_names(v, params))
        return out
    if isinstance(test, ast.Compare):
        if all(isinstance(op, (ast.Eq, ast.NotEq, ast.Lt, ast.LtE,
                               ast.Gt, ast.GtE)) for op in test.ops):
            return [n.id for n in [test.left] + test.comparators
                    if isinstance(n, ast.Name) and n.id in params]
    return []


def traced_branch_findings(project: Project) -> List[Finding]:
    out = []
    for key in sorted(_jit_roots(project)):
        fe = project.funcs.get(key)
        if fe is None:
            continue
        mod = project.modules[fe.modkey]
        params = {a.arg for a in fe.node.args.args if a.arg != "self"}
        for node in fe.own_nodes:
            test = None
            if isinstance(node, (ast.If, ast.While)):
                test = node.test
            elif isinstance(node, ast.Assert):
                test = node.test
            elif isinstance(node, ast.IfExp):
                test = node.test
            if test is None:
                continue
            names = _traced_names(test, params)
            if names:
                out.append(Finding(
                    "traced-branch", mod.path, node.lineno,
                    "jit function %r branches on traced %s — inside "
                    "jit this is a Python-level bool() of a tracer"
                    % (fe.qual, "/".join(sorted(set(names)))),
                    key="%s:%s" % (fe.qual,
                                   "/".join(sorted(set(names))))))
    return out


def timed_dispatch_findings(project: Project) -> List[Finding]:
    out = []
    for fe in project.funcs.values():
        mod = project.modules[fe.modkey]
        for line, has_dispatch, has_sync in fe.span_blocks:
            if has_dispatch and not has_sync:
                src = mod.lines[line - 1].strip() \
                    if line <= len(mod.lines) else ""
                out.append(Finding(
                    "timed-dispatch", mod.path, line,
                    "span times a jit dispatch with no "
                    "block_until_ready — measures dispatch, not "
                    "compute", key=src))
    return out


# ----------------------------------------------------------------------
# conf-key registry
# ----------------------------------------------------------------------

def conf_code_keys(project: Project) -> Dict[str, Tuple[str, int]]:
    keys: Dict[str, Tuple[str, int]] = {}

    def record(k, path, line):
        k = k.rstrip(":[-")
        if IDENT_RE.match(k) and k not in keys:
            keys[k] = (path, line)

    def scan(scope, path):
        for node in ast.walk(scope):
            if isinstance(node, ast.Compare) \
                    and isinstance(node.left, ast.Name) \
                    and node.left.id == "name" and len(node.ops) == 1:
                cmpv = node.comparators[0]
                if isinstance(node.ops[0], ast.Eq):
                    s = const_str(cmpv)
                    if s is not None:
                        record(s, path, node.lineno)
                elif isinstance(node.ops[0], ast.In) \
                        and isinstance(cmpv, (ast.Tuple, ast.List)):
                    for e in cmpv.elts:
                        s = const_str(e)
                        if s is not None:
                            record(s, path, node.lineno)
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "startswith" \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == "name":
                for a in node.args:
                    s = const_str(a)
                    if s is not None:
                        record(s, path, node.lineno)
                    elif isinstance(a, ast.Tuple):
                        for e in a.elts:
                            s = const_str(e)
                            if s is not None:
                                record(s, path, node.lineno)

    for fe in project.funcs.values():
        path = project.modules[fe.modkey].path
        argnames = {a.arg for a in fe.node.args.args}
        if "name" in argnames and ("val" in argnames
                                   or "value" in argnames):
            scan(fe.node, path)
        else:
            for node in fe.own_nodes:
                if isinstance(node, ast.For) \
                        and isinstance(node.target, ast.Tuple) \
                        and node.target.elts \
                        and isinstance(node.target.elts[0], ast.Name) \
                        and node.target.elts[0].id == "name":
                    scan(node, path)
    return keys


_FENCE_SKIP = {"python", "py", "bash", "sh", "json", "console", "text"}


def doc_conf_keys(doc_dir: str):
    """-> (texts {path: str}, registry {key: (path, line)}) — the
    registry is the STRICT documented set (key-table first cells +
    key = value lines in untagged fenced config examples)."""
    texts: Dict[str, str] = {}
    registry: Dict[str, Tuple[str, int]] = {}
    if not os.path.isdir(doc_dir):
        return texts, registry
    for fn in sorted(os.listdir(doc_dir)):
        if not fn.endswith(".md"):
            continue
        path = os.path.join(doc_dir, fn)
        with open(path, "r", encoding="utf-8") as f:
            text = f.read()
        texts[path] = text
        in_fence = False
        fence_tag = ""
        keycol = -1
        for i, line in enumerate(text.splitlines(), 1):
            ls = line.strip()
            if ls.startswith("```"):
                in_fence = not in_fence
                fence_tag = ls[3:].strip().lower() if in_fence else ""
                keycol = -1
                continue
            if in_fence:
                if fence_tag in _FENCE_SKIP and fence_tag:
                    continue
                m = re.match(r"^\s*([A-Za-z_][A-Za-z0-9_]*)\s*=\s*(.*)$",
                             line)
                if m and "(" not in m.group(2):
                    k = m.group(1)
                    if IDENT_RE.match(k) and k not in registry:
                        registry[k] = (path, i)
                continue
            if ls.startswith("|"):
                cells = [c.strip() for c in ls.strip("|").split("|")]
                lowered = [c.lower() for c in cells]
                if "key" in lowered or "config key" in lowered:
                    keycol = lowered.index("key") if "key" in lowered \
                        else lowered.index("config key")
                    continue
                if keycol >= 0 and all(set(c) <= set("-: ")
                                       for c in cells):
                    continue   # header separator row
                if keycol >= 0 and keycol < len(cells):
                    for tok in re.findall(r"`([^`]+)`", cells[keycol]):
                        tok = tok.split("[")[0].strip()
                        if IDENT_RE.match(tok) and tok not in registry:
                            registry[tok] = (path, i)
            else:
                keycol = -1
    return texts, registry


def conf_findings(project: Project, doc_dir: str) -> List[Finding]:
    out: List[Finding] = []
    texts, registry = doc_conf_keys(doc_dir)
    if not texts:
        return out
    code = conf_code_keys(project)
    for key in sorted(code):
        pat = re.compile(r"\b%s\b" % re.escape(key))
        if not any(pat.search(t) for t in texts.values()):
            path, line = code[key]
            out.append(Finding(
                "conf-undocumented", path, line,
                "conf key %r is read here but appears nowhere in "
                "doc/*.md" % key, key=key))
    for key in sorted(registry):
        if key not in code:
            path, line = registry[key]
            out.append(Finding(
                "conf-dead", path, line,
                "doc registers conf key %r but nothing in the package "
                "reads it" % key, key=key))
    return out


# ----------------------------------------------------------------------
# error vocabulary: the serving wire contract
# ----------------------------------------------------------------------

# the serving line protocol's error grammar is a CONTRACT: the fleet
# router dispatches retry/replay/relay on the `ERR <class> <detail>`
# third token, so an error string servd/routerd can emit that the
# doc/serving.md "### Error vocabulary" table does not list is a wire
# format nobody agreed to. The checker scans every string constant
# starting "ERR " in the two wire-speaking modules and matches it
# against the table's backticked `ERR ...` spans: `<placeholder>` and
# `(N)` doc tokens match any code token, `...` matches any tail,
# %-format code tokens match any doc token, and a code string that is
# a PREFIX of a row matches (builders append the detail at runtime).

ERR_VOCAB_MODULES = ("servd.py", "routerd.py")
ERR_SPAN_RE = re.compile(r"`(ERR [^`]+)`")


def _err_vocab_patterns(doc_dir: str) -> Optional[List[List[str]]]:
    path = os.path.join(doc_dir, "serving.md")
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError:
        return None
    m = re.search(r"^### Error vocabulary\s*$", text, re.M)
    if m is None:
        return None
    tail = text[m.end():]
    end = re.search(r"^#{2,3} ", tail, re.M)
    section = tail[:end.start()] if end else tail
    return [span.split()[1:] for span in ERR_SPAN_RE.findall(section)]


def _err_matches(pat: List[str], toks: List[str]) -> bool:
    i = 0
    for p in pat:
        if p == "...":
            return True
        if i >= len(toks):
            # the code string is a prefix of the row: the runtime
            # appends the detail ("ERR backend " + repr(e))
            return True
        if p.startswith("<") or p == "(N)" or "%" in toks[i]:
            i += 1
            continue
        if p != toks[i]:
            return False
        i += 1
    # an exact (wildcard-less) row must not leave a code tail unmatched
    return i >= len(toks)


def err_vocab_findings(project: Project, doc_dir: str) -> List[Finding]:
    out: List[Finding] = []
    pats = _err_vocab_patterns(doc_dir)
    if not pats:
        return out
    for mod in project.modules.values():
        if os.path.basename(mod.path) not in ERR_VOCAB_MODULES:
            continue
        seen = set()
        for node in mod.nodes:
            s = const_str(node)
            if s is None or not s.startswith("ERR ") \
                    or (s, node.lineno) in seen:
                continue
            seen.add((s, node.lineno))
            toks = s.split()[1:]
            if not toks:
                continue
            if not any(_err_matches(p, toks) for p in pats):
                out.append(Finding(
                    "err-vocab", mod.path, node.lineno,
                    "error string %r matches no row of doc/serving.md "
                    "'### Error vocabulary'" % s, key=s))
    return out


# ----------------------------------------------------------------------
# metric registry
# ----------------------------------------------------------------------

def metric_findings(project: Project) -> List[Finding]:
    out: List[Finding] = []
    series: Dict[str, dict] = {}
    for mod in project.modules.values():
        for node in mod.nodes:
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in METRIC_FUNCS:
                recv = dotted(f.value) or ""
                if recv not in ("telemetry", "reg", "self.reg") \
                        and not recv.endswith(".telemetry"):
                    continue
                name = const_str(node.args[0]) if node.args else None
                if name is None:
                    continue
                ent = series.setdefault(
                    name, {"types": set(), "site": (mod.path,
                                                    node.lineno)})
                ent["types"].add(METRIC_FUNCS[f.attr])
            elif isinstance(f, ast.Name) and f.id == "emit" \
                    and len(node.args) >= 2:
                name = const_str(node.args[0])
                mtype = const_str(node.args[1])
                if name is None or mtype is None:
                    continue
                if not PROM_NAME_RE.match(name):
                    out.append(Finding(
                        "metric-name", mod.path, node.lineno,
                        "invalid Prometheus metric name %r" % name,
                        key=name))
                if mtype == "counter" and not name.endswith("_total"):
                    out.append(Finding(
                        "metric-suffix", mod.path, node.lineno,
                        "Prometheus counter %r must end in _total"
                        % name, key=name))
    for name in sorted(series):
        ent = series[name]
        path, line = ent["site"]
        if not METRIC_NAME_RE.match(name):
            out.append(Finding(
                "metric-name", path, line,
                "telemetry series name %r outside [A-Za-z0-9_./]"
                % name, key=name))
        if len(ent["types"]) > 1:
            out.append(Finding(
                "metric-type", path, line,
                "series %r used as %s — one name, one type"
                % (name, " AND ".join(sorted(ent["types"]))), key=name))
        if "counter" in ent["types"] and name.endswith("_total"):
            out.append(Finding(
                "metric-suffix", path, line,
                "counter %r already ends in _total; statusd appends it"
                % name, key=name))
        if "histogram" in ent["types"] and name.endswith("_seconds"):
            out.append(Finding(
                "metric-suffix", path, line,
                "histogram %r already ends in _seconds; statusd "
                "appends it" % name, key=name))
    sanitized: Dict[str, Set[str]] = defaultdict(set)
    for name in series:
        sanitized[re.sub(r"[^A-Za-z0-9_]", "_", name)].add(name)
    for snm, raws in sorted(sanitized.items()):
        if len(raws) > 1:
            first = sorted(raws)[0]
            path, line = series[first]["site"]
            out.append(Finding(
                "metric-collision", path, line,
                "series %s all sanitize to the same Prometheus name "
                "cxxnet_%s" % (" / ".join(sorted(map(repr, raws))),
                               snm),
                key=snm))
    return out


# ----------------------------------------------------------------------
# metric documentation: the /metrics surface vs the doc tables
# ----------------------------------------------------------------------

# Every series statusd can export must appear (backticked) in one of the
# two operator-facing pages — the doc tables are what dashboards and
# alert rules are built from, so an undocumented series is a dashboard
# nobody can write. Exported names are derived exactly the way statusd
# derives them: telemetry series sanitize [^A-Za-z0-9_] -> '_', gain the
# cxxnet_ prefix, and counters/histograms gain _total/_seconds; literal
# emit() names are already full Prometheus names.
METRIC_DOC_FILES = ("observability.md", "serving.md")
# backticked spans AND fenced scrape examples both document a series, so
# the scan is any word-boundary occurrence in the two pages
METRIC_DOC_TOKEN_RE = re.compile(r"\b(cxxnet_[A-Za-z0-9_]+)")
METRIC_EXPORT_SUFFIX = {"counter": "_total", "gauge": "",
                        "histogram": "_seconds"}


def _doc_metric_tokens(doc_dir: str) -> Optional[Set[str]]:
    toks: Set[str] = set()
    seen_any = False
    for fn in METRIC_DOC_FILES:
        try:
            with open(os.path.join(doc_dir, fn), encoding="utf-8") as f:
                text = f.read()
        except OSError:
            continue
        seen_any = True
        toks.update(METRIC_DOC_TOKEN_RE.findall(text))
    return toks if seen_any else None


def _transition_table(project: Project) -> Dict[str, str]:
    """autopsy.py's TRANSITION_EVENTS literal, read from the AST (the
    linter never imports the package)."""
    for mod in project.modules.values():
        if os.path.basename(mod.path) != "autopsy.py":
            continue
        for node in mod.nodes:
            if not isinstance(node, ast.Assign) or \
                    not isinstance(node.value, ast.Dict):
                continue
            names = [dotted(t) for t in node.targets]
            if "TRANSITION_EVENTS" not in names:
                continue
            table: Dict[str, str] = {}
            for k, v in zip(node.value.keys, node.value.values):
                ks, vs = const_str(k), const_str(v)
                if ks is not None and vs is not None:
                    table[ks] = vs
            return table
    return {}


def metric_doc_findings(project: Project,
                        doc_dir: str) -> List[Finding]:
    out: List[Finding] = []
    doc = _doc_metric_tokens(doc_dir)
    if doc is None:
        return out

    # exported name -> first (path, line) that creates it
    exported: Dict[str, Tuple[str, int]] = {}
    # transition kind -> field -> {const values seen} / first site
    latch_vals: Dict[str, Set[object]] = defaultdict(set)
    latch_site: Dict[str, Tuple[str, int]] = {}
    table = _transition_table(project)
    for mod in project.modules.values():
        for node in mod.nodes:
            if not isinstance(node, ast.Call) or not node.args:
                continue
            f = node.func
            fname = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None)
            if fname in METRIC_FUNCS:
                recv = dotted(f.value) if isinstance(f, ast.Attribute) \
                    else ""
                recv = recv or ""
                if recv not in ("telemetry", "reg", "self.reg") \
                        and not recv.endswith(".telemetry"):
                    continue
                name = const_str(node.args[0])
                if name is None:
                    continue
                prom = "cxxnet_" + re.sub(r"[^A-Za-z0-9_]", "_", name) \
                    + METRIC_EXPORT_SUFFIX[METRIC_FUNCS[fname]]
                exported.setdefault(prom, (mod.path, node.lineno))
            elif isinstance(f, ast.Name) and f.id == "emit" \
                    and len(node.args) >= 2:
                name = const_str(node.args[0])
                if name is not None and name.startswith("cxxnet_"):
                    exported.setdefault(name, (mod.path, node.lineno))
            if fname in ("record", "event") \
                    and isinstance(node.args[0], ast.Dict):
                d = node.args[0]
                kv = {const_str(k): v
                      for k, v in zip(d.keys, d.values)
                      if const_str(k) is not None}
                kind = const_str(kv.get("ev")) if "ev" in kv else None
                if kind in table and table[kind] in kv:
                    v = kv[table[kind]]
                    if isinstance(v, ast.Constant) \
                            and isinstance(v.value, int):
                        latch_vals[kind].add(bool(v.value))
                        latch_site.setdefault(kind,
                                              (mod.path, node.lineno))

    for prom in sorted(exported):
        if prom.startswith("cxxnet_selftest_"):
            continue    # selftest fixtures are not operator surface
        if prom not in doc:
            path, line = exported[prom]
            out.append(Finding(
                "metric-doc", path, line,
                "exported series %r appears in no backticked span of "
                "doc/{observability,serving}.md" % prom, key=prom))
    for kind in sorted(table):
        field = table[kind]
        vals = latch_vals.get(kind, set())
        missing = []
        if True not in vals:
            missing.append("set (%s=1)" % field)
        if False not in vals:
            missing.append("clear (%s=0)" % field)
        if missing:
            path, line = latch_site.get(
                kind, (os.path.join(ROOT, PKG, "utils", "autopsy.py"), 0))
            out.append(Finding(
                "metric-doc", path, line,
                "transition event %r has no constant %s record site"
                % (kind, " or ".join(missing)),
                key="latch:" + kind))
    return out


# ----------------------------------------------------------------------
# assembly: suppressions, baseline ratchet, CLI
# ----------------------------------------------------------------------

class LintResult:
    def __init__(self, project, findings, edges, suppressed):
        self.project = project
        self.findings: List[Finding] = findings
        self.edges = edges
        self.suppressed: List[Finding] = suppressed


def run_lint(root: str = ROOT, pkg: str = PKG,
             doc_dir: Optional[str] = None) -> LintResult:
    project = Project(root, pkg)
    analyze_all(project)
    findings: List[Finding] = []
    for err in project.parse_errors:
        findings.append(Finding("lock-cycle", err.split(":")[0], 0,
                                "file failed to parse: " + err,
                                key="parse-error"))
    edges, lf = lock_analysis(project)
    findings.extend(lf)
    findings.extend(thread_findings(project))
    findings.extend(wallclock_findings(project))
    findings.extend(donated_reuse_findings(project))
    findings.extend(traced_branch_findings(project))
    findings.extend(timed_dispatch_findings(project))
    findings.extend(conf_findings(
        project, doc_dir or os.path.join(root, "doc")))
    findings.extend(err_vocab_findings(
        project, doc_dir or os.path.join(root, "doc")))
    findings.extend(metric_findings(project))
    findings.extend(metric_doc_findings(
        project, doc_dir or os.path.join(root, "doc")))

    by_path = {m.path: m for m in project.modules.values()}
    kept: List[Finding] = []
    suppressed: List[Finding] = []
    for f in findings:
        mod = by_path.get(f.path)
        # a suppression covers its own line only — the comment-above
        # style is handled by _index_suppressions propagating the entry
        # to the first code line after the comment block; a blanket
        # "line above" lookup would let an INLINE suppression silently
        # cover the unrelated next statement too
        sup = mod.suppress.get(f.line) if mod is not None else None
        if sup is not None and (f.rule in sup[0] or "all" in sup[0]):
            suppressed.append(f)
        else:
            kept.append(f)
    for mod in project.modules.values():
        for line, (rules, reason) in sorted(mod.suppress.items()):
            if line <= len(mod.lines) \
                    and not SUPPRESS_RE.search(mod.lines[line - 1]):
                continue    # propagated block entry, not the comment
            if not reason:
                kept.append(Finding(
                    "bad-suppression", mod.path, line,
                    "suppression of %s carries no reason"
                    % ",".join(sorted(rules)),
                    key=",".join(sorted(rules))))
    kept.sort(key=lambda f: (f.path, f.line, f.rule))
    return LintResult(project, kept, edges, suppressed)


def counts_of(findings: List[Finding], root: str) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for f in findings:
        fp = f.fingerprint(root)
        out[fp] = out.get(fp, 0) + 1
    return out


def ratchet(findings: List[Finding], root: str,
            baseline: Dict[str, int]):
    """-> (new, grandfathered, stale): new = findings past the baseline
    allowance, grandfathered = findings the baseline covers, stale =
    baseline fingerprints whose real count shrank below the recorded
    one (the entry must shrink with the debt)."""
    current = counts_of(findings, root)
    allowance = dict(baseline)
    new: List[Finding] = []
    grandfathered: List[Finding] = []
    for f in findings:
        fp = f.fingerprint(root)
        if allowance.get(fp, 0) > 0:
            allowance[fp] -= 1
            grandfathered.append(f)
        else:
            new.append(f)
    stale = sorted(fp for fp, n in baseline.items()
                   if current.get(fp, 0) < n)
    return new, grandfathered, stale


def load_baseline(path: str) -> Dict[str, int]:
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    return {str(k): int(v) for k, v in data.items()}


def topo_ranks(edges) -> List[str]:
    nodes = sorted({n for e in edges for n in e})
    indeg = {n: 0 for n in nodes}
    for (a, b) in edges:
        if a != b:
            indeg[b] += 1
    order: List[str] = []
    ready = sorted(n for n in nodes if indeg[n] == 0)
    adj = defaultdict(list)
    for (a, b) in edges:
        if a != b:
            adj[a].append(b)
    while ready:
        n = ready.pop(0)
        order.append(n)
        for m in sorted(adj[n]):
            indeg[m] -= 1
            if indeg[m] == 0:
                ready.append(m)
                ready.sort()
    return order


def selftest(verbose: bool = False) -> int:
    """The make-check gate: every package file parses, the full lint of
    the clean tree agrees with the shipped baseline, and the whole run
    stays fast enough to gate every PR (<5s)."""
    t0 = time.monotonic()
    res = run_lint()
    n_mod = len(res.project.modules)
    assert n_mod > 10, "package walk found only %d modules" % n_mod
    assert not res.project.parse_errors, \
        "analyzer failed to parse: %r" % res.project.parse_errors
    new, _, stale = ratchet(res.findings, ROOT, load_baseline(BASELINE))
    for f in new:
        sys.stderr.write(f.render(ROOT) + "\n")
    assert not new and not stale, (
        "clean tree is not clean: %d new finding(s), %d stale baseline "
        "entr(ies)" % (len(new), len(stale)))
    dt = time.monotonic() - t0
    assert dt < 5.0, "full-package lint took %.2fs (budget 5s)" % dt
    assert res.edges, "lock graph came out empty — resolution broke"
    if verbose:
        print("cxxlint selftest: %d modules parsed, %d lock edges, "
              "%d suppressed finding(s), clean in %.2fs"
              % (n_mod, len(res.edges), len(res.suppressed), dt))
    return 0


def main(argv: List[str]) -> int:
    if "--selftest" in argv:
        return selftest(verbose=True)
    verbose = "-v" in argv or "--verbose" in argv
    res = run_lint()
    if "--lock-graph" in argv:
        for (a, b), (path, line) in sorted(res.edges.items()):
            print("%s -> %s   (%s:%d)"
                  % (a, b, os.path.relpath(path, ROOT), line))
        return 0
    if "--ranks" in argv:
        for i, n in enumerate(topo_ranks(res.edges)):
            print("%-28s %d" % (n, (i + 1) * 10))
        return 0
    if "--update-baseline" in argv:
        counts = counts_of(res.findings, ROOT)
        with open(BASELINE, "w", encoding="utf-8") as f:
            json.dump(dict(sorted(counts.items())), f, indent=1,
                      sort_keys=True)
            f.write("\n")
        print("cxxlint: baseline updated: %d fingerprint(s), %d "
              "finding(s)" % (len(counts), sum(counts.values())))
        return 0
    baseline = load_baseline(BASELINE)
    new, grandfathered, stale = ratchet(res.findings, ROOT, baseline)
    for f in new:
        print(f.render(ROOT))
    if verbose:
        for f in grandfathered:
            print("baseline: " + f.render(ROOT).splitlines()[0])
        for f in res.suppressed:
            print("suppressed: " + f.render(ROOT).splitlines()[0])
    for fp in stale:
        print("stale baseline entry (fix landed — delete it from "
              "tools/cxxlint_baseline.json): %s" % fp)
    status = 1 if (new or stale) else 0
    print("cxxlint: %d finding(s) (%d new, %d grandfathered, %d "
          "suppressed), %d stale baseline entr%s -> %s"
          % (len(res.findings), len(new), len(grandfathered),
             len(res.suppressed), len(stale),
             "y" if len(stale) == 1 else "ies",
             "FAIL" if status else "ok"))
    return status


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
