#!/usr/bin/env python
"""Analytic FLOPs + MFU accounting for the model zoo (VERDICT r3 items 1/5:
"no MFU accounting" / "per-model MFU%").

Counts matmul-class FLOPs per image/token from each net's weight shapes and
node geometry (the same counting rule the scaling literature uses: 2*MACs
forward; training = 3x forward for the fwd + dgrad + wgrad passes), then
converts a measured images/sec rate into MFU% against the chip's bf16 peak.

Usage:
  python tools/roofline.py                # FLOPs/img table for the zoo
  python tools/roofline.py --bench f.json # + MFU% from bench JSON lines
                                          #   (BENCH_r*.json or onchip_logs)
  python tools/roofline.py --rate googlenet=4700 --rate alexnet=18300

The elementwise/pool/norm ops are NOT counted (sub-1% of FLOPs on every zoo
model); their cost shows up as the gap between MFU% and 100%, which is the
point of the metric.
"""

import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np

# The chip peak constants live in the SHARED DeviceSpec table
# (cxxnet_tpu/utils/perf.py) the live program ledger also reads — the
# offline MFU/decode-bound numbers and the runtime gauges can never
# disagree. PALLAS_AXON_TPU_GEN picks the generation (default v5e);
# CXXNET_PEAK_TFLOPS / CXXNET_PEAK_HBM_GBS override any entry.
from cxxnet_tpu.utils import perf


def peak_flops() -> float:
    return perf.offline_spec().peak_flops


def peak_hbm_bytes() -> float:
    return perf.offline_spec().hbm_bw


def net_flops_per_sample(tr) -> float:
    """Forward matmul-class FLOPs for ONE sample of the trainer's net.

    conv:   2 * prod(wmat.shape) * Ho * Wo   (wmat is (g, co/g, ci/g*k*k))
    fullc:  2 * prod(wmat.shape)
    moe:    2 * E * din * dout (dense dispatch — every expert runs)
    attention: 4 * L * W * d_model score+AV FLOPs (W = attn_window or L)
               + 2 * L * prod per projection weight (applied per position)
    embed:  0 (gather).  Shared layers count once per APPLICATION.
    """
    net, cfg = tr.net, tr.net.cfg
    batch = float(tr.batch_size)
    total = 0.0
    params = tr.canonical_params() if hasattr(tr, "canonical_params") \
        else tr.params
    for i, lay in enumerate(net.layers):
        info = cfg.layers[i]
        pidx = info.primary_layer_index if net.is_shared[i] else i
        p = params[pidx]
        tname = getattr(lay, "type_name", "")
        if tname == "embed":
            continue
        f = 0.0
        for key, w in p.items():
            shape = np.shape(w)
            if key in getattr(lay, "state_keys", lambda: ())():
                continue
            if len(shape) < 2:
                continue
            f += 2.0 * float(np.prod(shape))
        if tname == "conv" and info.nindex_out:
            b, c, h, w_ = net.node_shapes[info.nindex_out[0]]
            f *= h * w_
        if tname == "attention":
            b, d, _, L = net.node_shapes[info.nindex_in[0]]
            win = getattr(lay, "attn_window", 0) or L
            causal = getattr(lay, "causal", 0)
            span = min(win, L)
            # wqkv/wo projections apply per position, like conv's Ho*Wo
            f *= L
            # scores + AV: 2 ops each over (L x span x d); causal halves
            f += (2.0 if causal else 4.0) * L * span * d
        total += f
    return total


def zoo(models=None):
    """(name, trainer-builder, unit) for the bench rows. Construct on CPU
    — FLOPs are shape arithmetic; no TPU needed."""
    from cxxnet_tpu import models as M

    def lm(L, extra=""):
        return lambda: M.transformer_lm_trainer(
            vocab=8192, seq=L, batch_size=2, dim=512, nhead=8, nlayer=4,
            dev="cpu", extra_cfg="eval_train = 0\n" + extra)

    table = [
        ("alexnet", lambda: M.alexnet_trainer(8, 227, dev="cpu"), "img"),
        ("googlenet", lambda: M.googlenet_trainer(8, 224, dev="cpu"), "img"),
        ("resnet18", lambda: M.resnet_trainer(8, 224, dev="cpu"), "img"),
        ("vgg16", lambda: M.vgg_trainer(8, 224, dev="cpu"), "img"),
        ("mobilenet", lambda: M.mobilenet_trainer(8, 224, dev="cpu"),
         "img"),
        ("vit_s16", lambda: M.vit_trainer(
            n_class=1000, image_hw=224, patch=16, dim=384, nhead=6,
            nlayer=12, ffn_mult=4, batch_size=8, dev="cpu"), "img"),
        ("transformer_lm_L2048", lm(2048), "token"),
        ("transformer_lm_L8192_gqa_window",
         lm(8192, "nkvhead = 2\nattn_window = 1024\nrope = 1\n"), "token"),
    ]
    out = []
    for name, build, unit in table:
        if models and name not in models:
            continue
        try:
            tr = build()
        except Exception as e:   # model not constructible here: skip, say so
            print("# %s: skipped (%s)" % (name, e), file=sys.stderr)
            continue
        f = net_flops_per_sample(tr)
        if unit == "token":
            f /= tr.net.cfg.param.input_shape[2]   # per-token, not per-seq
        out.append((name, f, unit))
    return out


def decode_bound(tr, batch, prompt_len, gen_to, dtype_bytes=2):
    """Analytic tokens/sec bound for KV-cached greedy decode.

    Decode is HBM-bandwidth-bound, not FLOPs-bound: every step must read
    the full parameter set once (shared by the batch) plus each stream's
    KV cache up to the current position. bytes/step =
      params*dtype + B * sum_layers 2*kv_dim*min(t, window)*dtype,
    averaged over t in [prompt_len, gen_to). Bound = B * BW / avg_bytes.
    Embedding tables are a GATHER at decode — B rows read per step, not
    the whole table (mirroring the FLOPs model's "embed: 0" rule) — so
    they are excluded from the params term and charged per-row instead.
    Weight-shared attention applications each keep their own cache
    (decode keys caches by connection), so shared layers count per
    application here, unlike the params term."""
    net = tr.net
    params = tr.canonical_params() if hasattr(tr, "canonical_params") \
        else tr.params
    seen = set()
    param_bytes = 0.0
    embed_row_bytes = 0.0
    for i, lay in enumerate(net.layers):
        pidx = net.cfg.layers[i].primary_layer_index \
            if net.is_shared[i] else i
        if pidx in seen:
            continue
        seen.add(pidx)
        for key, w in params[pidx].items():
            sh = np.shape(w)
            if getattr(lay, "type_name", "") == "embed":
                # gather: one (d,)-row per stream per step
                embed_row_bytes += float(sh[-1] if sh else 1) * dtype_bytes
            else:
                param_bytes += float(np.prod(sh)) * dtype_bytes
    ts = np.arange(prompt_len, gen_to, dtype=np.float64)
    kv_read = np.zeros_like(ts)
    for i, lay in enumerate(net.layers):
        if getattr(lay, "type_name", "") != "attention":
            continue
        b, d, _, L = net.node_shapes[net.cfg.layers[i].nindex_in[0]]
        nkv = getattr(lay, "nkvhead", 0) or lay.nhead
        kv_dim = nkv * (d // lay.nhead)
        win = getattr(lay, "attn_window", 0) or gen_to
        kv_read += 2.0 * kv_dim * np.minimum(ts, win) * dtype_bytes
    avg_step_bytes = param_bytes + batch * (float(kv_read.mean())
                                            + embed_row_bytes)
    return batch * peak_hbm_bytes() / avg_step_bytes, param_bytes


def decode_zoo():
    """(name, builder, batch, prompt, gen_to) mirroring bench_lm_decode —
    the serving configs whose measured tokens/sec the bound judges."""
    from cxxnet_tpu import models as M

    def lm(L, extra=""):
        return lambda: M.transformer_lm_trainer(
            vocab=8192, seq=L, batch_size=2, dim=512, nhead=8, nlayer=4,
            dev="cpu", extra_cfg="eval_train = 0\n" + extra)

    return [
        ("lm_decode", lm(2048), 8, 64, 2048),
        ("lm_decode_b1", lm(2048), 1, 64, 2048),
        ("lm_decode_L8192_gqa_window",
         lm(8192, "nkvhead = 2\nattn_window = 1024\nrope = 1\n"),
         8, 64, 8192),
    ]


def decode_table(rates):
    bw = peak_hbm_bytes()
    print("| config | params MiB (bf16) | avg bytes/token | bound tok/s "
          "| measured tok/s | % of bound |")
    print("|---|---|---|---|---|---|")
    for name, build, batch, plen, gen_to in decode_zoo():
        try:
            tr = build()
        except Exception as e:
            print("# %s: skipped (%s)" % (name, e), file=sys.stderr)
            continue
        bound, pbytes = decode_bound(tr, batch, plen, gen_to)
        r = rates.get(name)
        meas = ("%.0f" % r) if r else "queued"
        pct = ("%.1f%%" % (100.0 * r / bound)) if r else "—"
        print("| %s (b%d, %d->%d) | %.1f | %.2fM | %.0f | %s | %s |"
              % (name, batch, plen, gen_to, pbytes / 2**20,
                 bw / bound / 1e6, bound, meas, pct))
    print("\n(bytes/token = bytes/step / batch; "
          "bound = B * HBM_BW / (params + B*avg KV read) bytes/step; "
          "HBM %.0f GB/s. MFU-style FLOPs are the wrong decode yardstick "
          "— a batch-8 decode reads ~all params per token.)"
          % (bw / 1e9))


_RATE_KEYS = {
    "lm_decode_tokens_per_sec": "lm_decode",
    "lm_decode_b1_tokens_per_sec": "lm_decode_b1",
    "lm_decode_L8192_tokens_per_sec": "lm_decode_L8192_gqa_window",
    "alexnet_imagenet_b1024": "alexnet",
    "alexnet_imagenet": "alexnet",
    "googlenet_imagenet": "googlenet",
    "resnet18_imagenet": "resnet18",
    "vgg16_imagenet": "vgg16",
    "mobilenet_imagenet": "mobilenet",
    "vit_s16": "vit_s16",
    "transformer_lm_L2048": "transformer_lm_L2048",
    "transformer_lm_L8192_gqa_window": "transformer_lm_L8192_gqa_window",
}


def _iter_bench_rows(raw):
    """Every {metric, ...} row in a bench capture, BOTH shapes: the
    driver wrapper ({"parsed": ..., "tail": "<JSONL>"}) the
    BENCH_r*.json files use, and raw bench.py / onchip JSONL. No
    dedup — repeated rounds of one metric in one log all come through
    (the caller keeps the best rate per model)."""
    try:
        doc = json.loads(raw)
    except ValueError:
        doc = None
    blobs = [raw]
    if isinstance(doc, dict):
        blobs = [doc.get("tail") or ""]
        if "metric" in doc:
            yield doc
        parsed = doc.get("parsed")
        if isinstance(parsed, dict) and "metric" in parsed:
            yield parsed
    elif isinstance(doc, list):
        blobs = []
        for d in doc:
            if isinstance(d, dict) and "metric" in d:
                yield d
    for blob in blobs:
        for line in blob.splitlines():
            line = line.strip()
            if not (line.startswith("{") and '"metric"' in line):
                continue
            try:
                d = json.loads(line)
            except ValueError:
                continue
            if isinstance(d, dict) and "metric" in d:
                yield d


def rates_from_bench(paths):
    """Parse {metric, value} bench rows (BENCH_r*.json wrapper files or
    raw JSONL like onchip_logs/*.log); keep the BEST rate per model
    across every occurrence. Returns ``(rates, n_null)`` — n_null
    counts the metrics whose every occurrence carried a null value (the
    structured non-result a down TPU tunnel produces; a metric that
    also measured somewhere is not "skipped"), and main() prints it:
    the MFU table must say how much of the trajectory it is not seeing,
    not silently render em-dashes."""
    rates = {}
    null_metrics = set()
    measured = set()
    for path in paths:
        with open(path) as f:
            raw = f.read()
        for row in _iter_bench_rows(raw):
            name = str(row.get("metric", ""))
            v = row.get("value")
            if v is None:
                null_metrics.add(name)
                continue
            if not v:
                continue
            measured.add(name)
            for prefix, model in _RATE_KEYS.items():
                if name.startswith(prefix):
                    rates[model] = max(rates.get(model, 0.0), float(v))
                    break
    return rates, len(null_metrics - measured)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--bench", action="append", default=[],
                    help="bench JSON-lines file(s) to pull measured rates")
    ap.add_argument("--rate", action="append", default=[],
                    help="model=samples_per_sec override")
    ap.add_argument("--decode", action="store_true",
                    help="print the decode bandwidth-bound table instead")
    ap.add_argument("models", nargs="*")
    args = ap.parse_args()
    os.environ.setdefault("CXXNET_JAX_PLATFORM", "cpu")

    rates, n_null = rates_from_bench(args.bench)
    if n_null:
        print("# %d bench row(s) skipped: value null (backend "
              "unreachable) — measured/s and MFU%% columns cover only "
              "the remaining rows" % n_null)
    for spec in args.rate:
        k, v = spec.split("=")
        rates[k] = float(v)

    if args.decode:
        decode_table(rates)
        return

    peak = peak_flops()
    print("| model | fwd GFLOPs/%s | train GFLOPs/%s | measured/s | MFU%% |"
          % ("sample", "sample"))
    print("|---|---|---|---|---|")
    for name, f, unit in zoo(args.models or None):
        train_f = 3.0 * f
        r = rates.get(name)
        mfu = "%.1f%%" % (100.0 * r * train_f / peak) if r else "—"
        rs = ("%.0f" % r) if r else "—"
        print("| %s | %.2f | %.2f | %s | %s |"
              % (name, f / 1e9, train_f / 1e9, rs, mfu))
    if not rates:
        print("\n(no measured rates given: pass --bench BENCH_r04.json or "
              "--rate model=N; MFU = rate * train_flops / %.0fT peak)"
              % (peak / 1e12))


if __name__ == "__main__":
    main()
