#!/usr/bin/env python
"""Build a reference-format .lst (``index label relpath``) from a
class-per-directory image tree, optionally holding out a validation split.

Usage: make_imglist.py <image_root> <train.lst> [val_frac] [val.lst]

Counterpart of the ad-hoc list-building steps in the reference's example
READMEs (example/kaggle_bowl/README.md, example/ImageNet/README.md); class
ids are assigned by sorted directory name, and the split is a seeded
Bernoulli draw per file (reproducible; with very small classes a class can
land entirely in train — acceptable for held-out evaluation).
"""

import os
import sys

EXTS = (".jpg", ".jpeg", ".png", ".bmp")


def build(root, train_lst, val_frac=0.0, val_lst=None, seed=42):
    assert val_frac == 0.0 or val_lst, \
        "val_frac set but no val.lst path given — the split would be lost"
    classes = sorted(d for d in os.listdir(root)
                     if os.path.isdir(os.path.join(root, d)))
    assert classes, "no class directories under %s" % root
    import random
    rnd = random.Random(seed)
    idx = 0
    n_tr = n_va = 0
    ftr = open(train_lst, "w")
    fva = open(val_lst, "w") if val_lst else None
    try:
        for label, cname in enumerate(classes):
            cdir = os.path.join(root, cname)
            for fname in sorted(os.listdir(cdir)):
                if not fname.lower().endswith(EXTS):
                    continue
                line = "%d\t%d\t%s\n" % (idx, label,
                                         os.path.join(cname, fname))
                if fva is not None and rnd.random() < val_frac:
                    fva.write(line)
                    n_va += 1
                else:
                    ftr.write(line)
                    n_tr += 1
                idx += 1
    finally:
        ftr.close()
        if fva:
            fva.close()
    return len(classes), n_tr, n_va


if __name__ == "__main__":
    if len(sys.argv) < 3:
        print(__doc__)
        sys.exit(1)
    root, train_lst = sys.argv[1], sys.argv[2]
    val_frac = float(sys.argv[3]) if len(sys.argv) > 3 else 0.0
    val_lst = sys.argv[4] if len(sys.argv) > 4 else None
    nc, ntr, nva = build(root, train_lst, val_frac, val_lst)
    print("%d classes, %d train, %d val" % (nc, ntr, nva))
