#!/usr/bin/env python
"""Build a reference-format .lst (``index label relpath``) from a
class-per-directory image tree, optionally holding out a validation split.

Usage: make_imglist.py <image_root> <train.lst> [val_frac] [val.lst]
       make_imglist.py --flat <image_dir> <out.lst>
       make_imglist.py --classes-from <sample_submission.csv> <root> \
                       <train.lst> [val_frac] [val.lst]

Counterpart of the ad-hoc list-building steps in the reference's example
READMEs (example/kaggle_bowl/README.md + gen_img_list.py,
example/ImageNet/README.md); class ids are assigned by sorted directory
name, and the split is a seeded Bernoulli draw per file (reproducible;
with very small classes a class can land entirely in train — acceptable
for held-out evaluation).

``--flat`` lists an unlabeled flat directory (label 0 for every file) —
the test-set mode of the reference's gen_img_list.py, for pred/pred_raw
iterators. ``--classes-from`` assigns class ids in a Kaggle submission
header's column order instead of sorted-directory order, so pred_raw
rows line up with the scored columns without reordering.
"""

import os
import sys

EXTS = (".jpg", ".jpeg", ".png", ".bmp")


def build_flat(image_dir, out_lst):
    """Unlabeled flat-directory list (label 0), sorted by filename."""
    files = sorted(f for f in os.listdir(image_dir)
                   if f.lower().endswith(EXTS))
    assert files, "no images under %s" % image_dir
    with open(out_lst, "w") as fo:
        for idx, fname in enumerate(files):
            fo.write("%d\t0\t%s\n" % (idx, fname))
    return len(files)


def classes_from_submission(csv_path):
    """Class order from a Kaggle sample-submission header (first column
    is the image name; the rest are class names in scoring order)."""
    import csv as _csv
    with open(csv_path) as f:
        header = next(_csv.reader(f))
    assert len(header) > 1, "submission header has no class columns"
    return header[1:]


def build(root, train_lst, val_frac=0.0, val_lst=None, seed=42,
          classes=None):
    assert val_frac == 0.0 or val_lst, \
        "val_frac set but no val.lst path given — the split would be lost"
    if classes is None:
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
    assert classes, "no class directories under %s" % root
    for cname in classes:
        assert os.path.isdir(os.path.join(root, cname)), (
            "class %r (from the submission header) has no directory "
            "under %s" % (cname, root))
    import random
    rnd = random.Random(seed)
    idx = 0
    n_tr = n_va = 0
    ftr = open(train_lst, "w")
    fva = open(val_lst, "w") if val_lst else None
    try:
        for label, cname in enumerate(classes):
            cdir = os.path.join(root, cname)
            for fname in sorted(os.listdir(cdir)):
                if not fname.lower().endswith(EXTS):
                    continue
                line = "%d\t%d\t%s\n" % (idx, label,
                                         os.path.join(cname, fname))
                if fva is not None and rnd.random() < val_frac:
                    fva.write(line)
                    n_va += 1
                else:
                    ftr.write(line)
                    n_tr += 1
                idx += 1
    finally:
        ftr.close()
        if fva:
            fva.close()
    return len(classes), n_tr, n_va


if __name__ == "__main__":
    args = sys.argv[1:]
    if args and args[0] == "--flat":
        if len(args) < 3:
            print(__doc__)
            sys.exit(1)
        n = build_flat(args[1], args[2])
        print("%d images (flat, label 0)" % n)
        sys.exit(0)
    classes = None
    if args and args[0] == "--classes-from":
        if len(args) < 2:
            print(__doc__)
            sys.exit(1)
        classes = classes_from_submission(args[1])
        args = args[2:]
    if len(args) < 2:
        print(__doc__)
        sys.exit(1)
    root, train_lst = args[0], args[1]
    val_frac = float(args[2]) if len(args) > 2 else 0.0
    val_lst = args[3] if len(args) > 3 else None
    nc, ntr, nva = build(root, train_lst, val_frac, val_lst,
                         classes=classes)
    print("%d classes, %d train, %d val" % (nc, ntr, nva))
