/*!
 * im2bin — pack an image list into BinaryPage .bin files.
 *
 * Native counterpart of the reference tool (reference: tools/im2bin.cpp):
 * reads a .lst file of `index label... filename` lines, appends each image
 * file's raw bytes into fixed-size BinaryPages, and writes the page stream
 * to the output .bin. The produced files are interchangeable with the
 * Python tools/im2bin.py and readable by the imgbin/imgbinx iterators.
 *
 * Usage: im2bin image.lst image_root output.bin [label_width] [page_ints]
 */
#include "../src/core/cxn_core.h"

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <sstream>
#include <string>
#include <vector>

int main(int argc, char *argv[]) {
  if (argc < 4) {
    std::fprintf(stderr,
                 "usage: im2bin image.lst image_root output.bin "
                 "[label_width=1] [page_ints=%lld]\n",
                 static_cast<long long>(64 << 18));
    return 1;
  }
  const std::string lst_path = argv[1];
  const std::string root = argv[2];
  const std::string out_path = argv[3];
  const int label_width = argc > 4 ? std::atoi(argv[4]) : 1;
  const int64_t page_ints = argc > 5 ? std::atoll(argv[5]) : (64 << 18);

  std::ifstream lst(lst_path);
  if (!lst) {
    std::fprintf(stderr, "im2bin: cannot open %s\n", lst_path.c_str());
    return 1;
  }
  void *page = CXNPageCreate(page_ints);
  // first page truncates the output, later pages append
  bool first = true;
  int64_t count = 0;
  std::string line;
  std::vector<char> bytes;
  while (std::getline(lst, line)) {
    if (line.find_first_not_of(" \t\r\n") == std::string::npos) continue;
    std::istringstream ss(line);
    std::string tok, fname;
    ss >> tok;  // index
    for (int i = 0; i < label_width; ++i) ss >> tok;  // labels
    ss >> fname;
    std::string path = root + fname;
    std::ifstream img(path, std::ios::binary);
    if (!img) {
      std::fprintf(stderr, "im2bin: cannot open image %s\n", path.c_str());
      return 1;
    }
    img.seekg(0, std::ios::end);
    std::streamoff sz = img.tellg();
    img.seekg(0);
    bytes.resize(size_t(sz));
    img.read(bytes.data(), sz);
    if (!CXNPagePush(page, bytes.data(), sz)) {
      if (!CXNPageSave(page, out_path.c_str(), first ? 0 : 1)) {
        std::fprintf(stderr, "im2bin: write error on %s\n", out_path.c_str());
        return 1;
      }
      first = false;
      CXNPageClear(page);
      if (!CXNPagePush(page, bytes.data(), sz)) {
        std::fprintf(stderr, "im2bin: image larger than a page: %s\n",
                     path.c_str());
        return 1;
      }
    }
    if (++count % 1000 == 0)
      std::fprintf(stderr, "%lld images packed\n",
                   static_cast<long long>(count));
  }
  if (CXNPageCount(page) != 0) {
    if (!CXNPageSave(page, out_path.c_str(), first ? 0 : 1)) {
      std::fprintf(stderr, "im2bin: write error on %s\n", out_path.c_str());
      return 1;
    }
  }
  CXNPageFree(page);
  std::fprintf(stderr, "im2bin: packed %lld images into %s\n",
               static_cast<long long>(count), out_path.c_str());
  return 0;
}
