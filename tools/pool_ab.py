#!/usr/bin/env python
"""Paired A/B: XLA select-and-scatter max-pool backward (default) vs the
fused Pallas backward (CXXNET_POOL=pallas) on GoogLeNet — the pool-heavy
bench model (select-and-scatter measured ~20% of its NCHW step). Adjacent
runs so shared-chip drift cancels; one JSON line per variant.

Usage: python tools/pool_ab.py [batch]
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".."))

from layout_ab import BF16, measure  # shared A/B measurement protocol


def main():
    from cxxnet_tpu.utils import enable_compile_cache
    enable_compile_cache()
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    for knob in ("", "pallas"):
        if knob:
            os.environ["CXXNET_POOL"] = knob
        else:
            os.environ.pop("CXXNET_POOL", None)
        from cxxnet_tpu.models import googlenet_trainer
        tr = googlenet_trainer(batch_size=batch, input_hw=224, dev="tpu",
                               extra_cfg=BF16)
        ips = measure(tr, (3, 224, 224), 1000, batch, steps=30)
        print(json.dumps({"variant": "googlenet_b%d_pool_%s"
                          % (batch, knob or "xla"),
                          "img_per_sec": round(ips, 1)}), flush=True)


if __name__ == "__main__":
    main()
