#!/usr/bin/env python
"""Paired A/B: XLA select-and-scatter max-pool backward (default) vs the
equality-mask custom VJP (CXXNET_POOL=mask, reference-exact unpool tie
semantics) on GoogLeNet — the pool-heavy bench model. Adjacent runs so
shared-chip drift cancels; one JSON line per variant.

History: a fused Pallas backward (CXXNET_POOL=pallas) also lived here
through r4; its r5 on-chip A/B measured 2,435 img/s vs 4,707 for
select-and-scatter (b128 bf16) and the kernel was deleted. The mask VJP
remains the semantics reference (measured ~2x slower, r3).

Usage: python tools/pool_ab.py [batch]
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".."))

from layout_ab import BF16, measure  # shared A/B measurement protocol


def main():
    from cxxnet_tpu.utils import enable_compile_cache
    enable_compile_cache()
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    for knob in ("", "mask"):
        if knob:
            os.environ["CXXNET_POOL"] = knob
        else:
            os.environ.pop("CXXNET_POOL", None)
        from cxxnet_tpu.models import googlenet_trainer
        tr = googlenet_trainer(batch_size=batch, input_hw=224, dev="tpu",
                               extra_cfg=BF16)
        ips = measure(tr, (3, 224, 224), 1000, batch, steps=30)
        print(json.dumps({"variant": "googlenet_b%d_pool_%s"
                          % (batch, knob or "xla"),
                          "img_per_sec": round(ips, 1)}), flush=True)


if __name__ == "__main__":
    main()
