#!/usr/bin/env python
"""Paired channels_last A/B on the bench models (adjacent runs, so
shared-chip drift cancels). One JSON line per variant.

Usage: python tools/layout_ab.py [vgg|alexnet|googlenet|resnet|all]
Default: the two variants still unmeasured (vgg b64, alexnet b1024).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np

BF16 = "eval_train = 0\ncompute_dtype = bfloat16\n"


def measure(tr, shape, nclass, batch, steps=15):
    """Shared A/B measurement protocol (pool_ab.py imports it too so the
    two tools' numbers stay methodology-comparable): device-resident
    batch, 3-step warmup, value-fetch sync (block_until_ready does not
    sync through the axon tunnel), best of two timed passes."""
    import jax
    import jax.numpy as jnp
    from cxxnet_tpu.io.data import DataBatch
    rs = np.random.RandomState(0)
    b = DataBatch()
    b.data = jax.device_put(rs.rand(batch, *shape).astype(np.float32))
    b.label = jax.device_put(
        rs.randint(0, nclass, (batch, 1)).astype(np.float32))
    b.batch_size = batch

    def sync():
        float(jnp.sum(next(v for p in tr.params for v in p.values())))

    for _ in range(3):
        tr.update(b)
    sync()
    best = 0.0
    for _ in range(2):
        t0 = time.perf_counter()
        for _ in range(steps):
            tr.update(b)
        sync()
        best = max(best, steps * batch / (time.perf_counter() - t0))
    return best


def ab(name, make, shape, nclass, batch, steps=15):
    for cl in (0, 1):
        tr = make("channels_last = %d\n" % cl)
        ips = measure(tr, shape, nclass, batch, steps)
        print(json.dumps({"variant": "%s_cl%d" % (name, cl),
                          "img_per_sec": round(ips, 1)}), flush=True)


def main():
    from cxxnet_tpu.utils import enable_compile_cache
    enable_compile_cache()
    from cxxnet_tpu import models as M
    which = sys.argv[1] if len(sys.argv) > 1 else "default"
    if which in ("vgg", "all", "default"):
        ab("vgg16_b64", lambda e: M.vgg_trainer(
            batch_size=64, input_hw=224, dev="tpu", remat=1,
            extra_cfg=BF16 + e), (3, 224, 224), 1000, 64)
    if which in ("alexnet", "all", "default"):
        ab("alexnet_b1024", lambda e: M.alexnet_trainer(
            batch_size=1024, input_hw=227, dev="tpu",
            extra_cfg=BF16 + e), (3, 227, 227), 1000, 1024)
    if which in ("googlenet", "all"):
        ab("googlenet_b128", lambda e: M.googlenet_trainer(
            batch_size=128, input_hw=224, dev="tpu",
            extra_cfg=BF16 + e), (3, 224, 224), 1000, 128, steps=30)
    if which in ("resnet", "all"):
        ab("resnet18_b128", lambda e: M.resnet_trainer(
            batch_size=128, input_hw=224, dev="tpu",
            extra_cfg=BF16 + e), (3, 224, 224), 1000, 128, steps=30)


if __name__ == "__main__":
    main()
