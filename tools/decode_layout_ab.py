#!/usr/bin/env python
"""Micro-A/B of the KV-cached decode attention step's cache layout.

The r5 decode trace (onchip_logs/decode_trace.log) shows ~80% of each
step in the score/AV matvecs at ~33% of HBM bandwidth. Hypothesis: the
(b, h, L, d) cache keeps d = 64 as the physical minor dim, so every
(8, 128) vector tile is half padding. Candidates:

  a) current    — K, V as (b, h, L, d);   scores 'bhqd,bhld->bhql'
  b) flat-minor — K, V as (b, L, h*d);    per-head math via reshape
  c) kT         — K as (b, h, d, L), V as (b, h, L, d)

Each variant runs the same 4-layer-equivalent read volume (one layer
here, x1983 steps in the scan is what generate does; we time 512
chained single steps). Measured GB/s is the verdict.

Usage: python tools/decode_layout_ab.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    from cxxnet_tpu.utils import enable_compile_cache
    enable_compile_cache()

    b, h, L, d = 8, 8, 2048, 64
    dt = jnp.bfloat16
    rs = np.random.RandomState(0)
    q = jax.device_put(rs.rand(b, h, 1, d).astype(np.float32)).astype(dt)
    k_bhld = jax.device_put(rs.rand(b, h, L, d).astype(np.float32)).astype(dt)
    v_bhld = jax.device_put(rs.rand(b, h, L, d).astype(np.float32)).astype(dt)
    k_flat = k_bhld.transpose(0, 2, 1, 3).reshape(b, L, h * d)
    v_flat = v_bhld.transpose(0, 2, 1, 3).reshape(b, L, h * d)
    k_t = k_bhld.transpose(0, 1, 3, 2)   # (b, h, d, L)
    scale = d ** -0.5
    read_bytes = 2 * b * h * L * d * 2   # K + V, bf16

    def step_a(q, k, v):
        s = jnp.einsum("bhqd,bhld->bhql", q, k) * scale
        p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(dt)
        return jnp.einsum("bhql,bhld->bhqd", p, v)

    def step_b(q, kf, vf):
        k = kf.reshape(b, L, h, d).transpose(0, 2, 1, 3)
        v = vf.reshape(b, L, h, d).transpose(0, 2, 1, 3)
        return step_a(q, k, v)

    def step_c(q, kt, v):
        s = jnp.einsum("bhqd,bhdl->bhql", q, kt) * scale
        p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(dt)
        return jnp.einsum("bhql,bhld->bhqd", p, v)

    def run(label, f, *args):
        n = 512

        def many(q0, *rest):
            def body(c, _):
                # carry-dependent q so XLA can't hoist the body out
                o = f(q0 + (c * 0).astype(q0.dtype), *rest)
                return c + jnp.sum(o.astype(jnp.float32)), None
            acc, _ = jax.lax.scan(body, jnp.float32(0), None, length=n)
            return acc
        c = jax.jit(many).lower(*args).compile()
        float(c(*args))
        t0 = time.perf_counter()
        r = c(*args)
        float(r)
        dt_s = (time.perf_counter() - t0) / n
        print("%-12s %8.1f us/step  %6.1f GB/s (K+V read)"
              % (label, dt_s * 1e6, read_bytes / dt_s / 1e9), flush=True)

    run("a_bhld", step_a, q, k_bhld, v_bhld)
    run("b_flat", step_b, q, k_flat, v_flat)
    run("c_kT", step_c, q, k_t, v_bhld)


if __name__ == "__main__":
    main()
