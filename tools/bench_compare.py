#!/usr/bin/env python
"""Gate on benchmark throughput regressions.

Usage:
    python tools/bench_compare.py [--threshold 0.10] [--dir REPO]
                                  [--bench FILE] [--baseline FILE]

Diffs the newest ``BENCH_*.json`` (the driver's per-round bench capture:
``{"parsed": <last line>, "tail": "<all emitted lines>"}`` — raw
``bench.py`` output files work too) against the committed numbers in
``BASELINE.json``'s ``"published"`` map (metric name -> value). Exit
codes:

* 0 — no regression, or nothing comparable: a metric whose measured value
  is ``null`` (e.g. the "backend unreachable" rows a down TPU tunnel
  produces) or that has no published baseline is SKIPPED cleanly, never
  failed — an unreachable backend is a structured non-result, not a
  regression.
* 1 — usage / unreadable input.
* 2 — at least one metric regressed by more than ``--threshold``
  (default 10%). "Regressed" respects the metric's direction: lower is
  worse for throughput rows, HIGHER is worse for latency rows (unit
  ``ms`` or a metric name containing ``latency``).

To start gating a metric, copy a trusted run's value into
``BASELINE.json``: ``"published": {"alexnet_imagenet_images_per_sec_per_chip":
15047.0}``. Sub-fields of a row gate too, opt-in per field, when the
baseline publishes ``"<metric>.<field>"`` — e.g.
``"serve_loopback_p99_latency_ms.ttft_p99_ms": 40.0`` gates the serve
row's TTFT tail, and
``"serve_fleet_p99_latency_ms.ttft_p99_ms"`` /
``".retry_rate"`` gate the routed-fleet row's tail and retry pressure
(the fleet TTFT comes from the router↔replica trace-id join), and
``"serve_throughput_rps.autopsy_compile_stall_pct"`` /
``".books_violations"`` gate the flood's compile-stall share and the
conservation-law auditor's violation count (both worse when HIGHER)
(direction-aware: ``*_ms`` / ``*_rate`` sub-fields are
worse when higher; null values skip cleanly like headline rows).
"""

import glob
import json
import os
import re
import sys


def find_newest_bench(dirname):
    """Newest BENCH_*.json by the rNN round number (mtime breaks ties —
    and orders any non-rNN names)."""
    cands = glob.glob(os.path.join(dirname, "BENCH_*.json"))
    if not cands:
        return None

    def key(p):
        m = re.search(r"BENCH_r(\d+)\.json$", os.path.basename(p))
        return (int(m.group(1)) if m else -1, os.path.getmtime(p))

    return max(cands, key=key)


def extract_lines(doc, raw_text=""):
    """Bench result lines from either capture shape: the driver wrapper
    ({"parsed": ..., "tail": "..."}) or raw bench.py JSONL output."""
    lines = []
    if isinstance(doc, dict) and "metric" in doc:
        lines.append(doc)
    if isinstance(doc, dict):
        for blob in (doc.get("tail") or "", raw_text):
            for ln in blob.splitlines():
                ln = ln.strip()
                if not ln.startswith("{"):
                    continue
                try:
                    d = json.loads(ln)
                except ValueError:
                    continue
                if isinstance(d, dict) and "metric" in d:
                    lines.append(d)
        parsed = doc.get("parsed")
        if isinstance(parsed, dict) and "metric" in parsed:
            lines.append(parsed)
    if isinstance(doc, list):
        lines.extend(d for d in doc if isinstance(d, dict)
                     and "metric" in d)
    # last occurrence of each metric wins (the driver keeps the headline
    # line last; tail may repeat it)
    out = {}
    for d in lines:
        out[d["metric"]] = d
    return list(out.values())


def lower_is_better(line):
    m = str(line.get("metric", ""))
    # the cold-start family (serve_cold_start_to_ready_s /
    # serve_scale_up_to_first_token_s / serve_reload_capacity_dip):
    # seconds-to-useful and capacity lost to recompiles — worse when
    # HIGHER even though the unit is s / ratio, not ms
    if (m.endswith("_to_ready_s") or m.endswith("_to_first_token_s")
            or m.endswith("_capacity_dip")):
        return True
    return line.get("unit") == "ms" or "latency" in m


def sub_lower_is_better(key, line):
    """Direction for a sub-field gated as ``<metric>.<key>``: latency
    sub-fields (``*_ms``, ``*latency*``) and failure-rate sub-fields
    (``*_rate``) are worse when HIGHER, whatever the parent row's unit —
    ``ttft_p99_ms`` on a throughput row still gates as a latency.
    Conversely throughput/capacity sub-fields (``*_rps``,
    ``*tokens_per_s*``, ``*occupancy*``) are worse when LOWER even on a
    latency row — ``mean_batch_occupancy`` on the serve rows gates as
    the coalescing win it measures. ``noisy_shed_rate`` (the
    serve_tenant_isolation row) is the one rate that is worse when
    LOWER: it measures the weighted-fair policy actually shedding the
    flooding tenant — a drop means the flood is getting through to the
    victim. (``fleet_scale_admission_latency_s`` needs no special
    case: the ``latency`` rule already gates it as worse-when-higher.)
    Utilization sub-fields (``*_live_pct`` — kv_live_pct on the
    throughput row: the live share of the decode KV cache) are worse
    when LOWER too: a drop means more padding/dead-slot waste, the
    regression the paged-KV before/after baseline (ROADMAP item 2)
    watches. (``queue_age_p99_ms`` needs no special case: the
    ``*_ms`` rule already gates it as worse-when-higher.)"""
    k = str(key)
    if (k.endswith("_to_ready_s") or k.endswith("_to_first_token_s")
            or k.endswith("_capacity_dip")):
        # the cold-start family as sub-fields: same direction as the
        # headline rule — time-to-useful and recompile capacity loss
        # are worse when HIGHER whatever the parent row measures
        return True
    if "ready_programs_pct" in k:
        # warm-grid readiness (the compile-cliff account): a drop means
        # more of the program grid is cold at admission — worse LOWER
        return False
    if k == "autopsy_compile_stall_pct":
        # the autopsy's compile-stall share of flood wall time (the
        # serve_throughput_rps row): a rise means more of the flood sat
        # behind cold programs — worse when HIGHER, unlike the other
        # _pct sub-fields that measure utilization
        return True
    if k == "books_violations":
        # the conservation-law auditor's violation count for the run:
        # any rise above the published 0 is bookkeeping corruption
        return True
    if k == "noisy_shed_rate":
        return False
    if k.endswith("_rps") or "tokens_per_s" in k or "occupancy" in k \
            or k.endswith("_live_pct") or k.endswith("hit_rate") \
            or k.endswith("retained_pct") or k.endswith("_speedup"):
        # prefix_hit_rate (the paged-KV shared-prefix reuse share) is
        # the other rate that is worse when LOWER: a drop means prompt
        # tokens are being re-prefilled instead of shared.
        # kv_retained_pct (the retained-cache share on the multiturn
        # row) and ttft_speedup (warm/cold ratio) gate the same way: a
        # drop means the retained conversation cache stopped holding
        # mass / stopped paying
        return False
    if "ttft" in k:
        # ttft sub-fields are time-to-first-token latencies — worse
        # when HIGHER even when the name lacks the _ms suffix
        # (checked after _speedup: ttft_speedup is a ratio, not a time)
        return True
    if "availability" in k or k in ("replays", "hedges", "hedge_wins"):
        # failover health (the serve_chaos_availability /
        # serve_hedged_tail rows): availability percentages and the
        # replay/hedge engagement counters are worse when LOWER — a
        # drop toward zero means the failover datapath stopped firing
        # while the error-rate sub-fields rose to tell the same story
        return False
    if k.endswith("_ms") or "latency" in k or k.endswith("_rate"):
        return True
    return lower_is_better(line)


def compare(lines, published, threshold):
    """-> (regressions, compared, skipped) lists of printable rows."""
    regressions, compared, skipped = [], [], []

    def gate(name, value, base, lower_better, null_detail):
        """Classify one (measured, published) pair into exactly one of
        the three row lists — shared by headline values and sub-fields
        so null-safety and direction handling cannot drift."""
        if value is None:
            skipped.append((name, "measured value is null (%s)"
                            % null_detail))
            return
        if not base:
            skipped.append((name, "baseline is zero/null"))
            return
        try:
            value, base = float(value), float(base)
        except (TypeError, ValueError):
            # placeholder strings ('TBD') etc.: not comparable, never
            # a gate failure
            skipped.append((name, "non-numeric value/baseline "
                            "(%r vs %r)" % (value, base)))
            return
        if not base:
            skipped.append((name, "baseline is zero"))
            return
        ratio = value / base
        bad = (ratio > 1.0 + threshold) if lower_better \
            else (ratio < 1.0 - threshold)
        row = (name, base, value, ratio - 1.0)
        (regressions if bad else compared).append(row)

    for line in lines:
        metric = line.get("metric")
        base = published.get(metric)
        if base is None:
            if line.get("value") is None:
                # count the null separately even unbaselined: the
                # end-of-run summary tallies how many rows the backend
                # never measured
                skipped.append((metric, "no published baseline; "
                                "measured value is null (%s)"
                                % line.get("error", "no error recorded")))
            else:
                skipped.append((metric, "no published baseline"))
        else:
            gate(metric, line.get("value"), base, lower_is_better(line),
                 line.get("error", "no error recorded"))
        # sub-fields (ttft_p99_ms, queue_wait_p99_ms, p50_ms, shed_rate,
        # ...) gate when the baseline publishes "<metric>.<key>" —
        # opt-in per sub-field, null-safe like the headline value.
        # Driven by the PUBLISHED keys, not the line's: a bench refactor
        # that renames or drops a gated sub-field must surface as a
        # visible skip, not silently retire the gate
        for name in sorted(k for k in published
                           if k.startswith(metric + ".")):
            key = name[len(metric) + 1:]
            if key in line:
                gate(name, line.get(key), published[name],
                     sub_lower_is_better(key, line),
                     "sub-field not measured")
            else:
                skipped.append((name, "sub-field absent from bench "
                                "line (renamed or no longer emitted?)"))
    return regressions, compared, skipped


def main(argv):
    threshold = 0.10
    dirname = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
    bench_path = None
    baseline_path = None
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--threshold" and i + 1 < len(argv):
            threshold = float(argv[i + 1])
            i += 2
        elif a == "--dir" and i + 1 < len(argv):
            dirname = argv[i + 1]
            i += 2
        elif a == "--bench" and i + 1 < len(argv):
            bench_path = argv[i + 1]
            i += 2
        elif a == "--baseline" and i + 1 < len(argv):
            baseline_path = argv[i + 1]
            i += 2
        else:
            print(__doc__, file=sys.stderr)
            return 1
    if bench_path is None:
        bench_path = find_newest_bench(dirname)
        if bench_path is None:
            print("bench_compare: no BENCH_*.json in %s — nothing to "
                  "compare (ok)" % dirname)
            return 0
    if baseline_path is None:
        baseline_path = os.path.join(dirname, "BASELINE.json")
    try:
        with open(bench_path) as f:
            raw = f.read()
    except OSError as e:
        print("bench_compare: cannot read %s: %s" % (bench_path, e),
              file=sys.stderr)
        return 1
    try:
        doc = json.loads(raw)
    except ValueError:
        # raw bench.py output is one JSON object PER LINE, not one
        # document: extract_lines parses it line-by-line
        doc = {}
    published = {}
    if os.path.exists(baseline_path):
        try:
            with open(baseline_path) as f:
                published = json.load(f).get("published", {}) or {}
        except (OSError, ValueError) as e:
            print("bench_compare: cannot read %s: %s" % (baseline_path, e),
                  file=sys.stderr)
            return 1
    lines = extract_lines(doc, raw)
    if not lines:
        print("bench_compare: no bench result lines in %s (ok: nothing "
              "to gate)" % bench_path)
        return 0
    regressions, compared, skipped = compare(lines, published, threshold)
    print("bench_compare: %s vs %s (threshold %.0f%%)"
          % (os.path.basename(bench_path), os.path.basename(baseline_path),
             100 * threshold))
    for metric, base, value, delta in compared:
        print("  ok    %-48s %12.2f -> %12.2f (%+.1f%%)"
              % (metric, base, value, 100 * delta))
    for metric, why in skipped:
        print("  skip  %-48s %s" % (metric, why))
    for metric, base, value, delta in regressions:
        print("  REGRESSION %-43s %12.2f -> %12.2f (%+.1f%% > %.0f%%)"
              % (metric, base, value, 100 * delta, 100 * threshold))
    # HEADLINE nulls only: a null sub-field of a row that DID measure
    # (e.g. mfu_pct absent because the card analysis errored) is not a
    # backend outage and must not be labeled one
    nulls = [m for m, why in skipped
             if "measured value is null" in why
             and "sub-field not measured" not in why]
    if nulls:
        # the gate must SAY how much of the trajectory it is not
        # checking: an all-null round (tunnel down) otherwise reads as
        # a clean pass indistinguishable from a genuinely-gated one
        print("bench_compare: %d row(s) skipped: backend unreachable "
              "(measured value null) — %d row(s) actually gated"
              % (len(nulls), len(compared) + len(regressions)))
    if regressions:
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
