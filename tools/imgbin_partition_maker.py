#!/usr/bin/env python
"""Shard an imgbin corpus (.lst + .bin) into N worker partitions.

Counterpart of the reference's tools/imgbin-partition-maker.py, which emits a
Makefile re-packing an image list into per-worker shards for distributed
training (consumed via ``image_conf_prefix``/``image_conf_ids`` +
``dist_num_worker``, reference: src/io/iter_thread_imbin-inl.hpp:189-220).
This version shards directly: records are split round-robin-by-block so each
partition i gets ``out_prefix%i.lst`` + ``out_prefix%i.bin``, readable by the
imgbin/imgbinx iterators with ``image_conf_prefix=out_prefix`` and
``image_conf_ids=0-<n-1>``.

Usage: imgbin_partition_maker.py <in.lst> <in.bin> <npart> <out_prefix>
       [page_ints]
"""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".."))

from cxxnet_tpu.utils.binary_page import BinaryPage, KPAGE_INTS


def partition(lst_path: str, bin_path: str, npart: int, out_prefix: str,
              page_ints: int = KPAGE_INTS) -> int:
    """Split records contiguously: partition i gets records
    [i*ceil(n/npart), (i+1)*ceil(n/npart)), matching the contiguous
    rank-sharding the iterators use for multi-part lists."""
    lines = [ln for ln in open(lst_path) if ln.strip()]
    n = len(lines)
    step = (n + npart - 1) // npart
    # stream records out of the source bin in list order
    fbin = open(bin_path, "rb")
    page = None
    ptop = 0

    def next_obj():
        nonlocal page, ptop
        while page is None or ptop >= page.size():
            page = BinaryPage.load(fbin, page_ints)
            assert page is not None, "bin exhausted before list"
            ptop = 0
        obj = page[ptop]
        ptop += 1
        return obj

    for i in range(npart):
        lo, hi = min(i * step, n), min((i + 1) * step, n)
        out_lst = (out_prefix % i) + ".lst"
        out_bin = (out_prefix % i) + ".bin"
        with open(out_lst, "w") as fl:
            fl.writelines(lines[lo:hi])
        with open(out_bin, "wb") as fo:
            opage = BinaryPage(page_ints)
            for _ in range(lo, hi):
                data = next_obj()
                if not opage.push(data):
                    opage.save(fo)
                    opage.clear()
                    assert opage.push(data), "record larger than a page"
            if opage.size():
                opage.save(fo)
    fbin.close()
    return n


def main(argv):
    if len(argv) < 5:
        print(__doc__)
        return 1
    lst, binf, npart, prefix = argv[1], argv[2], int(argv[3]), argv[4]
    page_ints = int(argv[5]) if len(argv) > 5 else KPAGE_INTS
    if "%" not in prefix:
        prefix += "_%d"
    n = partition(lst, binf, npart, prefix, page_ints)
    print("partitioned %d records into %d shards at %s" % (n, npart, prefix))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
