#!/usr/bin/env python
"""MFU experiment sweep for the bench models (VERDICT r1 item 4).

Runs the synthetic train-step benchmark over a variant matrix (batch size,
compute dtype) and prints one JSON line per variant — the fast way to find
the throughput knee on real hardware before/after kernel or layout work.

Usage: python tools/mfu_experiments.py [alexnet|googlenet|resnet|all]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np

BF16 = "eval_train = 0\ncompute_dtype = bfloat16\n"
F32 = "eval_train = 0\n"


def measure(tr, shape, nclass, batch, steps=30):
    import jax
    import jax.numpy as jnp
    from cxxnet_tpu.io.data import DataBatch
    rs = np.random.RandomState(0)
    b = DataBatch()
    b.data = jax.device_put(rs.rand(batch, *shape).astype(np.float32))
    b.label = jax.device_put(
        rs.randint(0, nclass, (batch, 1)).astype(np.float32))
    b.batch_size = batch

    def sync():
        float(jnp.sum(next(v for p in tr.params for v in p.values())))

    for _ in range(3):
        tr.update(b)
    sync()
    t0 = time.perf_counter()
    for _ in range(steps):
        tr.update(b)
    sync()
    return steps * batch / (time.perf_counter() - t0)


def sweep_transformer():
    """Long-context LM throughput: tokens/sec at L=2048, bf16 flash
    attention (the attention path has no CNN-style img/s equivalent)."""
    import jax
    import jax.numpy as jnp
    from cxxnet_tpu.models import transformer_lm_trainer
    from cxxnet_tpu.io.data import DataBatch
    for batch, L in ((8, 2048), (4, 8192)):
        try:
            tr = transformer_lm_trainer(
                vocab=8192, seq=L, batch_size=batch, dim=512, nhead=8,
                nlayer=4, dev="tpu",
                extra_cfg="eval_train = 0\ncompute_dtype = bfloat16\n")
            rs = np.random.RandomState(0)
            b = DataBatch()
            b.data = rs.randint(0, 8192, (batch, 1, 1, L)).astype(
                np.float32)
            b.label = rs.randint(0, 8192, (batch, L)).astype(np.float32)
            b.batch_size = batch
            for _ in range(3):
                tr.update(b)
            float(jnp.sum(next(v for p in tr.params for v in p.values())))
            t0 = time.perf_counter()
            steps = 20
            for _ in range(steps):
                tr.update(b)
            float(jnp.sum(next(v for p in tr.params for v in p.values())))
            tps = steps * batch * L / (time.perf_counter() - t0)
            del tr
            print(json.dumps({"model": "transformer_lm", "batch": batch,
                              "seq": L, "dtype": "bf16",
                              "tokens_per_sec": round(tps, 1)}), flush=True)
        except Exception as exc:
            print(json.dumps({"model": "transformer_lm", "batch": batch,
                              "seq": L, "error": str(exc)[:200]}),
                  flush=True)


def sweep(model):
    from cxxnet_tpu.models import (alexnet_trainer, googlenet_trainer,
                                   resnet_trainer)
    if model == "alexnet":
        build, shape, variants = alexnet_trainer, (3, 227, 227), [
            (256, BF16), (512, BF16), (1024, BF16), (256, F32),
            # LRN ablation: Pallas banded matmul vs XLA reduce_window
            (256, BF16 + "#lrn=xla\n")]
    elif model == "googlenet":
        build, shape, variants = googlenet_trainer, (3, 224, 224), [
            (128, BF16), (256, BF16), (512, BF16),
            # fusion ablation: sibling 1x1s as one wide conv vs separate
            (256, BF16 + "fuse_sibling_convs = 0\n")]
    else:
        build, shape, variants = resnet_trainer, (3, 224, 224), [
            (128, BF16), (256, BF16)]
    hw = shape[1]
    for batch, extra in variants:
        lrn_xla = "#lrn=xla" in extra
        if lrn_xla:
            os.environ["CXXNET_LRN"] = "xla"
            extra = extra.replace("#lrn=xla\n", "")
        try:
            tr = build(batch_size=batch, input_hw=hw, dev="tpu",
                       extra_cfg=extra)
            ips = measure(tr, shape, 1000, batch)
            del tr
            print(json.dumps({
                "model": model, "batch": batch,
                "dtype": "bf16" if "bfloat16" in extra else "f32",
                "fused": 0 if "fuse_sibling_convs = 0" in extra else 1,
                "lrn": "xla" if lrn_xla else "default",
                "images_per_sec": round(ips, 1)}), flush=True)
        except Exception as exc:   # OOM etc: record and continue the sweep
            print(json.dumps({"model": model, "batch": batch,
                              "error": str(exc)[:200]}), flush=True)
        finally:
            os.environ.pop("CXXNET_LRN", None)


def main():
    from cxxnet_tpu.utils import enable_compile_cache
    enable_compile_cache()
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    if which == "transformer":
        sweep_transformer()
        return
    models = ("alexnet", "googlenet", "resnet") if which == "all" \
        else (which,)
    for m in models:
        sweep(m)
    if which == "all":
        sweep_transformer()


if __name__ == "__main__":
    main()
