#!/usr/bin/env python
"""Paired A/B: default inception module execution (sibling-fused 1x1
trio + separate pool-projection conv) vs cross-input 1x1 batching
(``fuse_cross_1x1 = 1``: the trio concat AND the pool-projection run as
ONE batched matmul — net.py _cross_1x1_plan). Targets the GoogLeNet
~23% MFU row (doc/performance.md): the per-module pool-proj matmul is
individually too small to fill the MXU. Adjacent runs so shared-chip
drift cancels; one JSON line per variant. Flip the trainer default only
if this wins on-chip.

Usage: python tools/cross1x1_ab.py [batch]
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".."))

from layout_ab import BF16, measure  # shared A/B measurement protocol


def main():
    from cxxnet_tpu.utils import enable_compile_cache
    enable_compile_cache()
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    from cxxnet_tpu.models import googlenet_trainer
    for knob in (0, 1):
        tr = googlenet_trainer(
            batch_size=batch, input_hw=224, dev="tpu",
            extra_cfg=BF16 + "fuse_cross_1x1 = %d\n" % knob)
        n_pairs = len(tr.net._cross_1x1_plan())
        ips = measure(tr, (3, 224, 224), 1000, batch, steps=30)
        print(json.dumps({"variant": "googlenet_b%d_cross1x1_%s"
                          % (batch, "on" if knob else "off"),
                          "batched_pairs": n_pairs,
                          "img_per_sec": round(ips, 1)}), flush=True)


if __name__ == "__main__":
    main()
