#!/usr/bin/env python
"""Per-model HBM memory report from XLA's compiled-program analysis.

Usage: python tools/memory_report.py [model]
           [--pp K|--zero|--fsdp|--tp K] [n_devices]

Compiles the model's train step (without executing it) and prints XLA's
memory_analysis(): argument (param/opt-state) bytes, temp (activation)
bytes, output bytes — per device. Run on the 8-device virtual CPU mesh
(no TPU needed: set JAX_PLATFORMS=cpu
XLA_FLAGS=--xla_force_host_platform_device_count=8) to see how the
parallelism keys change the per-device footprint:

  python tools/memory_report.py mlp            # replicated baseline
  python tools/memory_report.py mlp --zero     # ZeRO opt-state sharding
  python tools/memory_report.py mlp --pp 4     # stage-packed pipeline
  python tools/memory_report.py alexnet --tp 2 # Megatron fullc sharding
  python tools/memory_report.py deep --pp 4 --remat  # PP + activation
                                               # remat (the AD stash knob)

The PP case: AD differentiates through the fill-drain scan
(parallel/pipeline.py), stashing every tick's boundary activations plus
stage internals — n_micro + n_stages - 1 ticks of them. That stash is
XLA "temp" bytes here; ``--remat`` checkpoints every trunk layer so
the backward recomputes stage internals instead of stashing them (the
per-microbatch memory/compute trade: temp bytes down, ~1/3 more
FLOPs). ``deep`` is a uniform 16-layer trunk built for pp4.

This turns the ZeRO / pipeline memory claims (doc/multichip.md) into
measured bytes; tests/test_compose.py asserts the shard-size ratios, this
tool shows the absolute numbers for any config.
"""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".."))

# the env-var route (JAX_PLATFORMS) cannot undo a preloaded tunneled
# platform; the config route can (same pattern as bin/cxxnet)
_plat = os.environ.get("CXXNET_JAX_PLATFORM") or (
    "cpu" if os.environ.get("JAX_PLATFORMS", "").startswith("cpu")
    else None)
if _plat:
    import jax
    jax.config.update("jax_platforms", _plat)

import numpy as np


def build(model, extra):
    from cxxnet_tpu.models import (alexnet_trainer, googlenet_trainer,
                                   transformer_lm_trainer)
    from cxxnet_tpu.nnet.trainer import Trainer
    from cxxnet_tpu.utils.config import parse_config_string
    n = "tpu:0-%d" % (int(os.environ.get("_NDEV", "8")) - 1)
    if model == "alexnet":
        return alexnet_trainer(batch_size=32, input_hw=67, dev=n,
                               extra_cfg=extra), (32, 3, 67, 67), 1000
    if model == "googlenet":
        return googlenet_trainer(batch_size=16, input_hw=128, dev=n,
                                 extra_cfg=extra), (16, 3, 128, 128), 1000
    if model == "lm":
        tr = transformer_lm_trainer(vocab=512, seq=256, batch_size=8,
                                    dim=128, nhead=4, nlayer=2, dev=n,
                                    extra_cfg=extra)
        return tr, (8, 1, 1, 256), 512
    if model == "deep":
        # uniform 16-layer trunk: the natural pp4 customer; wide enough
        # (512) that the per-tick AD stash dominates the report
        conf = "netconfig = start\n"
        for i in range(16):
            conf += ("layer[+1] = fullc:d%d\n  nhidden = 512\n"
                     "  init_sigma = 0.05\n" % i)
            conf += "layer[+1] = relu\n"
        conf += """layer[+1] = fullc:head
  nhidden = 10
  init_sigma = 0.05
layer[+0] = softmax
netconfig = end
input_shape = 1,1,512
batch_size = 64
eta = 0.1
momentum = 0.9
dev = %s
""" % n + extra
        tr = Trainer()
        for k, v in parse_config_string(conf):
            tr.set_param(k, v)
        tr.init_model()
        return tr, (64, 1, 1, 512), 10
    conf = """
netconfig = start
layer[+1] = fullc:fc1
  nhidden = 512
  init_sigma = 0.05
layer[+1] = relu
layer[+1] = fullc:fc2
  nhidden = 256
  init_sigma = 0.05
layer[+1] = relu
layer[+1] = fullc:fc3
  nhidden = 10
  init_sigma = 0.05
layer[+0] = softmax
netconfig = end
input_shape = 1,1,784
batch_size = 64
eta = 0.1
momentum = 0.9
dev = %s
""" % n + extra
    tr = Trainer()
    for k, v in parse_config_string(conf):
        tr.set_param(k, v)
    tr.init_model()
    return tr, (64, 1, 1, 784), 10


def main():
    args = [a for a in sys.argv[1:]]
    model = args[0] if args and not args[0].startswith("--") else "mlp"
    extra = ""
    consumed = set()
    for flag, key in (("--pp", "pipeline_parallel"),
                      ("--micro", "pipeline_micro"),
                      ("--tp", "model_parallel")):
        if flag in args:
            i = args.index(flag)
            extra += "%s = %s\n" % (key, args[i + 1])
            consumed.add(i + 1)
    if "--zero" in args:
        extra += "update_on_server = 1\n"
    if "--fsdp" in args:
        extra += "fsdp = 1\n"
    if "--remat" in args:
        extra += "remat = 1\n"
    tail = [a for i, a in enumerate(args)
            if i > 0 and i not in consumed and a.isdigit()]
    ndev = int(tail[-1]) if tail else None

    import jax
    if ndev:
        os.environ["_NDEV"] = str(ndev)
    tr, shape, nclass = build(model, extra)
    from cxxnet_tpu.io.data import DataBatch
    rs = np.random.RandomState(0)
    b = DataBatch()
    if model == "lm":
        b.data = rs.randint(0, nclass, shape).astype(np.float32)
        b.label = rs.randint(0, nclass,
                             (shape[0], shape[3])).astype(np.float32)
    else:
        b.data = rs.rand(*shape).astype(np.float32)
        b.label = rs.randint(0, nclass, (shape[0], 1)).astype(np.float32)
    b.batch_size = shape[0]
    lowered = tr.lower_update(b)
    compiled = lowered.compile()
    m = compiled.memory_analysis()
    if m is None:
        print("backend exposes no memory_analysis()")
        return
    def gb(x):
        return "%.2f MiB" % (x / (1 << 20))
    print("model=%s extra=%r devices=%d" %
          (model, extra.replace("\n", " "), tr.mesh.devices.size
           if tr.mesh is not None else 1))
    print("  per-device argument (params+opt state):",
          gb(m.argument_size_in_bytes))
    print("  per-device temp (activations/workspace):",
          gb(m.temp_size_in_bytes))
    print("  per-device output:", gb(m.output_size_in_bytes))
    print("  generated code:", gb(m.generated_code_size_in_bytes))
    total = (m.argument_size_in_bytes + m.temp_size_in_bytes
             + m.output_size_in_bytes)
    print("  total per device:", gb(total))
    # headroom vs the shared DeviceSpec table (cxxnet_tpu/utils/perf.py
    # — the same capacity the live ledger's cxxnet_hbm_headroom_bytes
    # gauge reports, so offline sizing and runtime accounting agree)
    from cxxnet_tpu.utils import perf
    spec = perf.offline_spec()
    print("  %s HBM capacity: %s  ->  headroom: %s (%.1f%% used)"
          % (spec.name, gb(spec.hbm_capacity),
             gb(spec.hbm_capacity - total),
             100.0 * total / spec.hbm_capacity))


if __name__ == "__main__":
    main()
