#!/usr/bin/env python
"""Layout A/B: GoogLeNet fwd+bwd in plain jax, NCHW vs NHWC.

Isolates two questions the xprof trace can't answer directly:
  1. does an internal channels-last layout change TPU throughput for the
     inception topology (1x1-heavy, channel concats, stride-1 pool towers)?
  2. how much of the framework trainer's step time is framework overhead
     vs raw-jax ceiling for the same math?

Prints one JSON line per variant: {"variant", "img_per_sec"}.
Usage: python tools/layout_experiment.py [batch]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np

# (c1, c3r, c3, c5r, c5, pool_proj) per module — Inception-v1 Table 1
MODULES = [
    (64, 96, 128, 16, 32, 32),
    (128, 128, 192, 32, 96, 64),
    "pool",
    (192, 96, 208, 16, 48, 64),
    (160, 112, 224, 24, 64, 64),
    (128, 128, 256, 24, 64, 64),
    (112, 144, 288, 32, 64, 64),
    (256, 160, 320, 32, 128, 128),
    "pool",
    (256, 160, 320, 32, 128, 128),
    (384, 192, 384, 48, 128, 128),
]


def build(layout):
    import jax
    import jax.numpy as jnp
    from jax import lax

    if layout == "NHWC":
        dn = ("NHWC", "HWIO", "NHWC")
        caxis = 3
        pool_win = (1, 3, 3, 1)
    else:
        dn = ("NCHW", "OIHW", "NCHW")
        caxis = 1
        pool_win = (1, 1, 3, 3)

    def conv(x, w, stride=1, pad=0):
        return lax.conv_general_dilated(
            x, w, (stride, stride), [(pad, pad), (pad, pad)],
            dimension_numbers=dn)

    def maxpool(x, k=3, stride=2, pad=0):
        strides = ((1, stride, stride, 1) if caxis == 3
                   else (1, 1, stride, stride))
        padding = [(0, 0), (pad, pad), (pad, pad), (0, 0)] if caxis == 3 \
            else [(0, 0), (0, 0), (pad, pad), (pad, pad)]
        return lax.reduce_window(x, -jnp.inf, lax.max, pool_win,
                                 strides, padding)

    rs = np.random.RandomState(0)

    def wshape(kh, kw, cin, cout):
        if layout == "NHWC":
            return (kh, kw, cin, cout)
        return (cout, cin, kh, kw)

    def mkw(kh, kw, cin, cout):
        return jnp.asarray(
            rs.randn(*wshape(kh, kw, cin, cout)).astype(np.float32)
            * (1.0 / np.sqrt(kh * kw * cin)), jnp.bfloat16)

    params = {}
    params["stem1"] = mkw(7, 7, 3, 64)
    params["stem2r"] = mkw(1, 1, 64, 64)
    params["stem2"] = mkw(3, 3, 64, 192)
    cin = 192
    for i, m in enumerate(MODULES):
        if m == "pool":
            continue
        c1, c3r, c3, c5r, c5, cp = m
        params[f"m{i}_1"] = mkw(1, 1, cin, c1)
        params[f"m{i}_3r"] = mkw(1, 1, cin, c3r)
        params[f"m{i}_3"] = mkw(3, 3, c3r, c3)
        params[f"m{i}_5r"] = mkw(1, 1, cin, c5r)
        params[f"m{i}_5"] = mkw(5, 5, c5r, c5)
        params[f"m{i}_p"] = mkw(1, 1, cin, cp)
        cin = c1 + c3 + c5 + cp
    params["fc"] = jnp.asarray(
        rs.randn(cin, 1000).astype(np.float32) * 0.02, jnp.bfloat16)

    import jax.nn

    def fwd(params, x, labels):
        r = jax.nn.relu
        x = r(conv(x, params["stem1"], 2, 3))
        x = maxpool(x)
        x = r(conv(x, params["stem2r"]))
        x = r(conv(x, params["stem2"], 1, 1))
        x = maxpool(x)
        for i, m in enumerate(MODULES):
            if m == "pool":
                x = maxpool(x)
                continue
            t1 = r(conv(x, params[f"m{i}_1"]))
            t3 = r(conv(r(conv(x, params[f"m{i}_3r"])),
                        params[f"m{i}_3"], 1, 1))
            t5 = r(conv(r(conv(x, params[f"m{i}_5r"])),
                        params[f"m{i}_5"], 1, 2))
            tp = r(conv(maxpool(x, 3, 1, 1), params[f"m{i}_p"]))
            x = jnp.concatenate([t1, t3, t5, tp], axis=caxis)
        x = jnp.mean(x, axis=(1, 2) if caxis == 3 else (2, 3))
        logits = (x @ params["fc"]).astype(jnp.float32)
        lp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(lp, labels[:, None], 1))

    return params, fwd


def run(layout, batch, steps=20):
    import jax
    import jax.numpy as jnp

    params, fwd = build(layout)
    shape = (batch, 224, 224, 3) if layout == "NHWC" \
        else (batch, 3, 224, 224)
    rs = np.random.RandomState(1)
    x = jax.device_put(jnp.asarray(rs.rand(*shape), jnp.bfloat16))
    labels = jax.device_put(jnp.asarray(
        rs.randint(0, 1000, (batch,)), jnp.int32))

    @jax.jit
    def step(params, x, labels):
        g = jax.grad(fwd)(params, x, labels)
        return jax.tree.map(lambda p, g: p - 0.01 * g, params, g)

    for _ in range(3):
        params = step(params, x, labels)
    float(jnp.sum(params["fc"].astype(jnp.float32)))
    best = 0.0
    for _ in range(2):
        t0 = time.perf_counter()
        p = params
        for _ in range(steps):
            p = step(p, x, labels)
        float(jnp.sum(p["fc"].astype(jnp.float32)))
        best = max(best, steps * batch / (time.perf_counter() - t0))
    return best


def main():
    from cxxnet_tpu.utils import enable_compile_cache
    enable_compile_cache()
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    for layout in ("NCHW", "NHWC"):
        ips = run(layout, batch)
        print(json.dumps({"variant": "googlenet_raw_%s_b%d"
                          % (layout, batch),
                          "img_per_sec": round(ips, 1)}), flush=True)


if __name__ == "__main__":
    main()
