#!/usr/bin/env python
"""Convergence/quality evidence runner -> QUALITY.md.

Runs the example recipes (MNIST.conf, MNIST_CONV.conf, a bowl-shaped conv
recipe) to their full round counts and records final train/test error per
seed. The reference's quality claim is ~2% error on real MNIST after the
15-round MLP recipe (reference example/MNIST/README.md); this sandbox has
zero egress, so the corpora here are generated (tests/synth_mnist.py,
bit-identical idx format):

* easy  — the test-suite corpus (noise 20): every recipe must reach 0 error
  (capacity/sanity: the net memorizes a separable task through the full
  io -> augment -> trainer path).
* hard  — 10k/2k glyph images (make_glyph_dataset): each class is a
  distinct shape drawn at a jittered position over sigma-60 pixel noise.
  Like real MNIST, test error lands in the low percents for the conv
  recipe and conv beats the mlp by a wide margin (translation jitter is
  exactly what convolution's inductive bias buys); both must be stable
  across seeds.

Usage: python tools/quality_run.py [out.md]   (run from the repo root;
uses the live jax backend — TPU when the tunnel is up, else dev=cpu)
"""

import os
import re
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))

_RECIPE_CONF = """
data = train
iter = mnist
    path_img = "{dir}/train-images-idx3-ubyte.gz"
    path_label = "{dir}/train-labels-idx1-ubyte.gz"
    input_flat = 0
    shuffle = 1
iter = end
eval = test
iter = mnist
    input_flat = 0
    path_img = "{dir}/t10k-images-idx3-ubyte.gz"
    path_label = "{dir}/t10k-labels-idx1-ubyte.gz"
iter = end
{netconfig}
input_shape = 1,28,28
batch_size = 100
dev = {dev}
save_model = 0
{train_params}metric[label] = error
"""

_BOWL_NET = """netconfig=start
layer[0->1] = conv:c1
  kernel_size = 5
  nchannel = 16
  random_type = xavier
layer[1->2] = relu
layer[2->3] = max_pooling
  kernel_size = 3
  stride = 2
layer[3->4] = flatten
layer[4->5] = fullc:f1
  nhidden = 128
  random_type = xavier
layer[5->6] = relu
layer[6->7] = fullc:f2
  nhidden = 10
  random_type = xavier
layer[7->7] = softmax
netconfig=end"""

_BOWL_PARAMS = "max_round = 12\nnum_round = 12\neta = 0.05\n" \
    "momentum = 0.9\nwd = 0.0001\n"
_VIT_PARAMS = "max_round = 15\nnum_round = 15\nupdater = adamw\n" \
    "eta = 0.001\nwd = 0.01\n"



def run_cli(conf_path, overrides, cwd, dev="cpu"):
    cmd = [sys.executable, os.path.join(REPO, "bin", "cxxnet"),
           conf_path] + overrides
    env = dict(os.environ)
    if dev == "cpu":
        # pin the platform via the config route: with the axon tunnel
        # down, a cpu run that lets the preloaded plugin autodiscover
        # hangs in backend init instead of falling back (the env-var
        # route cannot undo a preloaded platform; this one can)
        env["CXXNET_JAX_PLATFORM"] = "cpu"
    t0 = time.time()
    p = subprocess.run(cmd, cwd=cwd, capture_output=True, text=True,
                       timeout=3600, env=env)
    assert p.returncode == 0, p.stdout + p.stderr
    text = p.stdout + p.stderr   # metric lines go to stderr (reference)
    rounds = re.findall(
        r"\[(\d+)\]\s+train-error:([\d.]+)\s+test-error:([\d.]+)", text)
    assert rounds, "no metric lines in output:\n" + text[-2000:]
    last = rounds[-1]
    return {"rounds": int(last[0]) + 1, "train_err": float(last[1]),
            "test_err": float(last[2]), "wall_s": round(time.time() - t0, 1)}


def backend():
    """Probe the live backend in a subprocess so a wedged TPU tunnel can't
    hang the harness — fall back to cpu after 90s."""
    try:
        p = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.default_backend())"],
            capture_output=True, text=True, timeout=90)
        out = p.stdout.strip().splitlines()
        return out[-1] if p.returncode == 0 and out else "cpu"
    except subprocess.TimeoutExpired:
        return "cpu"


def main():
    from cxxnet_tpu.utils import enable_compile_cache
    enable_compile_cache()
    out_path = sys.argv[1] if len(sys.argv) > 1 else \
        os.path.join(REPO, "QUALITY.md")
    from synth_mnist import make_dataset, make_glyph_dataset

    dev = "tpu" if backend() == "tpu" else "cpu"
    results = []

    with tempfile.TemporaryDirectory() as td:
        for corpus, maker, kw in (
                ("easy", make_dataset,
                 dict(n_train=600, n_test=200, noise=20.0)),
                ("hard", make_glyph_dataset,
                 dict(n_train=10000, n_test=2000))):
            for seed in (0, 1, 2):
                droot = os.path.join(td, "%s_s%d" % (corpus, seed))
                os.makedirs(os.path.join(droot, "data"))
                os.makedirs(os.path.join(droot, "models"), exist_ok=True)
                maker(os.path.join(droot, "data"), seed=seed, **kw)
                for name, conf, extra in (
                        ("mnist_mlp",
                         os.path.join(REPO, "example/MNIST/MNIST.conf"),
                         ["dev=%s" % dev, "seed=%d" % seed,
                          "save_model=0"]),
                        ("mnist_conv",
                         os.path.join(REPO, "example/MNIST/MNIST_CONV.conf"),
                         ["dev=%s" % dev, "seed=%d" % seed,
                          "save_model=0"]),
                ):
                    r = run_cli(conf, extra, droot, dev=dev)
                    r.update(recipe=name, corpus=corpus, seed=seed)
                    results.append(r)
                    print(r, flush=True)
                # bowl-shaped conv recipe (kaggle_bowl-like trunk)
                bowl = os.path.join(droot, "bowl_like.conf")
                with open(bowl, "w") as f:
                    f.write(_RECIPE_CONF.format(
                        dir=os.path.join(droot, "data"), dev=dev,
                        netconfig=_BOWL_NET, train_params=_BOWL_PARAMS))
                r = run_cli(bowl, ["seed=%d" % seed], droot, dev=dev)
                r.update(recipe="bowl_like_conv", corpus=corpus, seed=seed)
                results.append(r)
                print(r, flush=True)
                # ViT recipe (patch-embed conv -> im2seq -> attention):
                # the DSL-composed vision-transformer family end to end
                from cxxnet_tpu.models import vit_netconfig
                vit = os.path.join(droot, "vit_like.conf")
                with open(vit, "w") as f:
                    f.write(_RECIPE_CONF.format(
                        dir=os.path.join(droot, "data"), dev=dev,
                        netconfig=vit_netconfig(
                            10, image_hw=28, patch=4, dim=48,
                            nhead=4, nlayer=2),
                        train_params=_VIT_PARAMS))
                r = run_cli(vit, ["seed=%d" % seed], droot, dev=dev)
                r.update(recipe="vit_like", corpus=corpus, seed=seed)
                results.append(r)
                print(r, flush=True)

    # transformer-LM recipe (the long-context family, beyond the
    # reference): cyclic-walk corpus, 400 adam steps, next-token accuracy
    sys.path.insert(0, os.path.join(REPO, "example", "transformer"))
    import train_lm
    lm_rows = []
    for seed in (0, 1, 2):
        t0 = time.time()
        acc = train_lm.main(steps=400, dev=dev, seed=seed)
        lm_rows.append(dict(seed=seed, steps=400, acc=acc,
                            wall_s=time.time() - t0))
        print(lm_rows[-1], flush=True)

    lines = [
        "# QUALITY — convergence evidence",
        "",
        "Recipes run end-to-end through the CLI (`bin/cxxnet <conf>`) on "
        "backend **%s**; corpora generated by tests/synth_mnist.py (real "
        "MNIST is unreachable: zero-egress sandbox — the reference's ~2%% "
        "claim on real MNIST is reproduced in *structure*: low, "
        "seed-stable error on the hard corpus, 0 on the easy one, "
        "conv <= mlp)." % dev,
        "",
        "| recipe | corpus | seed | rounds | train err | test err | wall s |",
        "|---|---|---|---|---|---|---|",
    ]
    for r in results:
        lines.append("| %s | %s | %d | %d | %.4f | %.4f | %.1f |" % (
            r["recipe"], r["corpus"], r["seed"], r["rounds"],
            r["train_err"], r["test_err"], r["wall_s"]))

    lines.append("")
    lines.append("Transformer LM (example/transformer, cyclic-walk corpus, "
                 "400 adam steps):")
    lines.append("")
    lines.append("| recipe | seed | steps | next-token acc | wall s |")
    lines.append("|---|---|---|---|---|")
    for r in lm_rows:
        lines.append("| transformer_lm | %d | %d | %.4f | %.1f |" % (
            r["seed"], r["steps"], r["acc"], r["wall_s"]))

    # aggregate check lines
    import statistics as st
    lines.append("")
    for recipe in ("mnist_mlp", "mnist_conv", "bowl_like_conv",
                   "vit_like"):
        hard = [r["test_err"] for r in results
                if r["recipe"] == recipe and r["corpus"] == "hard"]
        easy = [r["test_err"] for r in results
                if r["recipe"] == recipe and r["corpus"] == "easy"]
        lines.append(
            "- **%s**: easy test err %s; hard test err mean %.4f "
            "(spread %.4f over 3 seeds)" % (
                recipe, easy, st.mean(hard),
                max(hard) - min(hard)))
    with open(out_path, "w") as f:
        f.write("\n".join(lines) + "\n")
    print("wrote", out_path)

    # acceptance criteria — regressions must FAIL the run, not just be
    # recorded (verify skill step 7 relies on the exit code)
    bad = []
    for r in results:
        if r["corpus"] == "easy" and r["test_err"] > 0.0:
            bad.append("easy-corpus error %.4f on %s seed %d"
                       % (r["test_err"], r["recipe"], r["seed"]))
    hards = {rec: [r["test_err"] for r in results
                   if r["recipe"] == rec and r["corpus"] == "hard"]
             for rec in ("mnist_mlp", "mnist_conv", "bowl_like_conv",
                         "vit_like")}
    if st.mean(hards["mnist_conv"]) > st.mean(hards["mnist_mlp"]):
        bad.append("conv does not beat mlp on the hard corpus")
    if st.mean(hards["mnist_conv"]) > 0.15:
        bad.append("conv hard error %.3f implausibly high"
                   % st.mean(hards["mnist_conv"]))
    for rec, errs in hards.items():
        if max(errs) - min(errs) > 0.1:
            bad.append("%s hard error unstable across seeds: %s"
                       % (rec, errs))
    lm_accs = [r["acc"] for r in lm_rows]
    if min(lm_accs) < 0.90:
        bad.append("transformer_lm next-token acc below 0.90: %s" % lm_accs)
    if st.mean(lm_accs) < 0.93:
        bad.append("transformer_lm mean acc %.3f below 0.93"
                   % st.mean(lm_accs))
    if bad:
        print("QUALITY REGRESSION:\n  " + "\n  ".join(bad))
        sys.exit(1)
    print("quality criteria met")


if __name__ == "__main__":
    main()
