#!/bin/bash
# Drive the queued on-chip verifications through the axon tunnel, in
# priority order, one TPU process at a time (two concurrent TPU processes
# can wedge the tunnel). Waits for the tunnel first, then runs each step
# with its own timeout, logging to onchip_logs/<step>.log and appending a
# one-line status to onchip_logs/STATUS. Safe to rerun: the persistent
# compile cache makes repeats cheap, and completed steps can be skipped
# with SKIP="kernels bench ...".
#
# Usage: bash tools/onchip_queue.sh [max_wait_seconds]
set -u
cd "$(dirname "$0")/.."
mkdir -p onchip_logs
MAX_WAIT=${1:-21600}
SKIP=${SKIP:-}

note() { echo "$(date -u +%F' '%T) $*" | tee -a onchip_logs/STATUS; }

# --- wait for the tunnel -------------------------------------------------
note "queue start; waiting for tunnel (max ${MAX_WAIT}s)"
waited=0
while true; do
  if timeout 3 bash -c 'echo > /dev/tcp/127.0.0.1/8093' 2>/dev/null; then
    if timeout 90 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
      note "tunnel UP (after ${waited}s)"
      break
    fi
  fi
  sleep 30; waited=$((waited + 30))
  if [ "$waited" -ge "$MAX_WAIT" ]; then
    note "tunnel still down after ${MAX_WAIT}s; giving up"
    exit 1
  fi
done

# --- steps ---------------------------------------------------------------
run() {
  name=$1; tmo=$2; shift 2
  case " $SKIP " in *" $name "*) note "$name SKIPPED"; return;; esac
  note "$name START: $*"
  timeout -k 60 "$tmo" "$@" > "onchip_logs/$name.log" 2>&1
  rc=$?
  note "$name DONE rc=$rc: $(tail -1 "onchip_logs/$name.log" | cut -c1-160)"
}

# priority order for SHORT tunnel windows: the headline bench first (the
# driver's BENCH_r* number), then the queued A/Bs, then the long sweeps
run bench    900  python bench.py
run kernels  900  python tools/check_tpu_kernels.py
run poolab   1500 python tools/pool_ab.py
run cross1x1 1500 python tools/cross1x1_ab.py
run layout   2400 python tools/layout_ab.py default
run benchall 5400 python bench.py all
run mfutable 600  python tools/roofline.py --bench onchip_logs/bench.log --bench onchip_logs/benchall.log
run decodetable 600 python tools/roofline.py --decode --bench onchip_logs/benchall.log
run pipeline 1200 python bench.py pipeline
run mfu      5400 python tools/mfu_experiments.py all
run quality  3600 python tools/quality_run.py
run profile  1200 python tools/profile_bench.py googlenet

note "queue finished"
