#!/usr/bin/env python
"""Summarize a Chrome-format trace: a jax profiler capture
(tools/profile_bench.py) OR a per-request serving trace
(statusd ``/trace?request=<id>``, utils/servd flight recorder).

Usage: python tools/summarize_trace.py <trace-dir-or-trace.json[.gz]>
                                       [top_n]

Profiler traces (plugins/profile/*/**.trace.json.gz): aggregates
complete events by name across the TensorCore lanes and prints the
top-N ops by total self duration — enough to rank hot HLO/fusion ops
without TensorBoard. No TPU or network needed.

Per-request traces (detected by their phase lanes — queue_wait /
dispatch / prefill / decode, doc/observability.md): prints the phase
split with percentages of the request's wall-clock, the recompiles the
request paid, and the phase coverage — the one-slow-request triage view
without opening Perfetto.
"""

import gzip
import glob
import json
import os
import sys
from collections import defaultdict

# the serving request-phase lanes (telemetry.REQUEST_PHASES — literal
# here so the tool stays dependency-free and runs on a bare checkout)
REQUEST_PHASES = ("queue_wait", "dispatch", "prefill", "decode")


def find_trace(path: str) -> str:
    if os.path.isfile(path):
        return path
    hits = sorted(glob.glob(os.path.join(
        path, "plugins", "profile", "*", "*.trace.json.gz")))
    if not hits:
        hits = sorted(glob.glob(os.path.join(path, "*.trace.json.gz")))
    if not hits:
        raise SystemExit("no *.trace.json.gz under %r" % path)
    return hits[-1]


def load_trace(path: str) -> dict:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        return json.load(f)


def summarize_request(events) -> None:
    """Per-request trace: phase table + recompiles + coverage."""
    xs = [e for e in events if e.get("ph") == "X" and "dur" in e]
    phases = [e for e in xs if e["name"] in REQUEST_PHASES]
    rid = outcome = "?"
    for e in phases:
        args = e.get("args") or {}
        rid = args.get("request", rid)
        outcome = args.get("outcome", outcome)
    # the phases TILE the request's wall-clock (utils/servd) — the
    # phase lane, not the recompile annotations, defines the total
    t0 = min(e["ts"] for e in phases or xs)
    t1 = max(e["ts"] + e["dur"] for e in phases or xs)
    total = max(t1 - t0, 1e-9)
    covered = sum(e["dur"] for e in phases)
    print("request %s (%s): total %.2fms" % (rid, outcome, total / 1e3))
    print("%-12s %10s %6s" % ("phase", "ms", "pct"))
    by_name = {e["name"]: e for e in phases}
    for name in REQUEST_PHASES:
        e = by_name.get(name)
        if e is not None:
            print("%-12s %10.2f %5.1f%%"
                  % (name, e["dur"] / 1e3, 100.0 * e["dur"] / total))
    comps = [e for e in xs if e["name"].startswith("compile:")]
    for e in comps:
        print("%-12s %10.2f        %s (%s)"
              % ("recompile", e["dur"] / 1e3, e["name"][len("compile:"):],
                 (e.get("args") or {}).get("cause", "?")))
    print("phase coverage: %.1f%% of request wall-clock"
          % (100.0 * covered / total))


def main():
    path = find_trace(sys.argv[1] if len(sys.argv) > 1 else "profile_out")
    top_n = int(sys.argv[2]) if len(sys.argv) > 2 else 25
    trace = load_trace(path)
    events = trace.get("traceEvents", [])
    if any(e.get("ph") == "X" and e.get("name") in REQUEST_PHASES
           for e in events):
        print("trace: %s" % path)
        summarize_request(events)
        return
    # name the process/thread lanes so we can keep device lanes only
    # (host-side Python/runtime lanes would double-count wall time)
    pids = {}
    tids = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pids[e["pid"]] = e["args"].get("name", "")
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            tids[(e["pid"], e.get("tid"))] = e["args"].get("name", "")
    dur_by_name = defaultdict(float)
    cnt_by_name = defaultdict(int)
    total = 0.0
    for e in events:
        if e.get("ph") != "X" or "dur" not in e:
            continue
        lane = (pids.get(e["pid"], "")
                + "/" + tids.get((e["pid"], e.get("tid")), ""))
        low = lane.lower()
        if not ("tpu" in low or "xla" in low or "tensorcore" in low
                or "/device" in low or "sparsecore" in low):
            continue
        if "step" in low:   # step-marker lanes duplicate op time
            continue
        name = e["name"]
        dur_by_name[name] += e["dur"]
        cnt_by_name[name] += 1
        total += e["dur"]
    rows = sorted(dur_by_name.items(), key=lambda kv: -kv[1])[:top_n]
    print("trace: %s" % path)
    print("device-lane total: %.1f ms over %d distinct ops"
          % (total / 1e3, len(dur_by_name)))
    print("%-72s %10s %8s %6s" % ("op", "total_ms", "calls", "pct"))
    for name, d in rows:
        print("%-72s %10.2f %8d %5.1f%%"
              % (name[:72], d / 1e3, cnt_by_name[name],
                 100.0 * d / max(total, 1e-9)))


if __name__ == "__main__":
    main()
