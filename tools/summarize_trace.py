#!/usr/bin/env python
"""Summarize a jax profiler trace captured by tools/profile_bench.py.

Usage: python tools/summarize_trace.py <trace-dir-or-trace.json.gz> [top_n]

Reads the Chrome-format trace (plugins/profile/*/**.trace.json.gz),
aggregates complete events by name across the TensorCore lanes, and
prints the top-N ops by total self duration — enough to rank hot
HLO/fusion ops without TensorBoard. No TPU or network needed.
"""

import gzip
import glob
import json
import os
import sys
from collections import defaultdict


def find_trace(path: str) -> str:
    if os.path.isfile(path):
        return path
    hits = sorted(glob.glob(os.path.join(
        path, "plugins", "profile", "*", "*.trace.json.gz")))
    if not hits:
        hits = sorted(glob.glob(os.path.join(path, "*.trace.json.gz")))
    if not hits:
        raise SystemExit("no *.trace.json.gz under %r" % path)
    return hits[-1]


def main():
    path = find_trace(sys.argv[1] if len(sys.argv) > 1 else "profile_out")
    top_n = int(sys.argv[2]) if len(sys.argv) > 2 else 25
    with gzip.open(path, "rt") as f:
        trace = json.load(f)
    events = trace.get("traceEvents", [])
    # name the process/thread lanes so we can keep device lanes only
    # (host-side Python/runtime lanes would double-count wall time)
    pids = {}
    tids = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pids[e["pid"]] = e["args"].get("name", "")
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            tids[(e["pid"], e.get("tid"))] = e["args"].get("name", "")
    dur_by_name = defaultdict(float)
    cnt_by_name = defaultdict(int)
    total = 0.0
    for e in events:
        if e.get("ph") != "X" or "dur" not in e:
            continue
        lane = (pids.get(e["pid"], "")
                + "/" + tids.get((e["pid"], e.get("tid")), ""))
        low = lane.lower()
        if not ("tpu" in low or "xla" in low or "tensorcore" in low
                or "/device" in low or "sparsecore" in low):
            continue
        if "step" in low:   # step-marker lanes duplicate op time
            continue
        name = e["name"]
        dur_by_name[name] += e["dur"]
        cnt_by_name[name] += 1
        total += e["dur"]
    rows = sorted(dur_by_name.items(), key=lambda kv: -kv[1])[:top_n]
    print("trace: %s" % path)
    print("device-lane total: %.1f ms over %d distinct ops"
          % (total / 1e3, len(dur_by_name)))
    print("%-72s %10s %8s %6s" % ("op", "total_ms", "calls", "pct"))
    for name, d in rows:
        print("%-72s %10.2f %8d %5.1f%%"
              % (name[:72], d / 1e3, cnt_by_name[name],
                 100.0 * d / max(total, 1e-9)))


if __name__ == "__main__":
    main()
