#!/usr/bin/env python
"""Capture an xprof trace of the benched train steps for MFU analysis.

Usage: python tools/profile_bench.py [alexnet|googlenet] [outdir]

Writes a jax profiler trace (xplane) under outdir (default
./profile_out/<model>); inspect hot ops with
tools/summarize_trace.py or TensorBoard's profile plugin offline.
"""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np


def main():
    from cxxnet_tpu.utils import enable_compile_cache
    enable_compile_cache()
    model = sys.argv[1] if len(sys.argv) > 1 else "alexnet"
    outdir = sys.argv[2] if len(sys.argv) > 2 else \
        os.path.join("profile_out", model)
    import jax
    import jax.numpy as jnp
    from cxxnet_tpu.models import alexnet_trainer, googlenet_trainer
    from cxxnet_tpu.io.data import DataBatch

    bf16 = "eval_train = 0\ncompute_dtype = bfloat16\n"
    if model == "alexnet":
        batch, hw = 256, 227
        tr = alexnet_trainer(batch_size=batch, input_hw=hw, dev="tpu",
                             extra_cfg=bf16)
    else:
        batch, hw = 128, 224
        tr = googlenet_trainer(batch_size=batch, input_hw=hw, dev="tpu",
                               extra_cfg=bf16)

    rs = np.random.RandomState(0)
    b = DataBatch()
    b.data = jax.device_put(rs.rand(batch, 3, hw, hw).astype(np.float32))
    b.label = jax.device_put(
        rs.randint(0, 1000, (batch, 1)).astype(np.float32))
    b.batch_size = batch

    for _ in range(3):               # compile + warm
        tr.update(b)
    float(jnp.sum(next(v for p in tr.params for v in p.values())))

    os.makedirs(outdir, exist_ok=True)
    with jax.profiler.trace(outdir):
        for _ in range(10):
            tr.update(b)
        float(jnp.sum(next(v for p in tr.params for v in p.values())))
    print("trace written to", outdir)


if __name__ == "__main__":
    main()
