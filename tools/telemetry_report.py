#!/usr/bin/env python
"""Summarize a telemetry JSONL run log (utils/telemetry.py).

Usage:
    python tools/telemetry_report.py run.jsonl [--top N] [--trace out.json]
                                               [--json]

Prints top spans by total time, recompile count/causes/seconds, per-round
breakdowns, counters/gauges, step-time percentiles, and a training-health
section (anomalies/rollbacks/watchdog stalls/corrupt records,
utils/health.py). ``--trace`` additionally exports a chrome://tracing /
Perfetto JSON built from the span tree. ``--json`` emits the aggregate as
one JSON object instead of the table (for scripting).

Exit codes: 0 ok; 1 usage / unreadable file; 2 malformed log (a line
that is not valid JSON, or no telemetry events at all) OR a log with
``health_anomaly`` events that no resolution event (``health_rollback``
/ ``health_skip`` / ``health_abort`` referencing the anomaly id, or an
inline ``resolution`` field) ever answered — CI gates on this so neither
a broken emitter nor an unrecovered training anomaly can silently pass.
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".."))

from cxxnet_tpu.utils.telemetry import (  # noqa: E402
    count_by, events_to_chrome, percentile)


def load_events(path):
    """Parse one-event-per-line JSONL; malformed lines are fatal (exit 2:
    the log writer is append-only, so a bad line means a broken emitter
    or a truncated copy — summarizing around it would lie)."""
    events = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError as e:
                print("%s:%d: malformed JSONL line: %s"
                      % (path, lineno, e), file=sys.stderr)
                sys.exit(2)
            if not isinstance(ev, dict):
                print("%s:%d: event is not a JSON object" % (path, lineno),
                      file=sys.stderr)
                sys.exit(2)
            events.append(ev)
    if not events:
        print("%s: no telemetry events" % path, file=sys.stderr)
        sys.exit(2)
    return events


def aggregate(events):
    spans = {}
    compiles = []
    counters = {}
    gauges = {}
    rounds = []
    health = {"anomalies": [], "resolutions": [], "stalls": [],
              "data_corrupt": 0, "skipped_batches": 0}
    for ev in events:
        kind = ev.get("ev")
        if kind == "span":
            a = spans.setdefault(ev["name"], [])
            a.append(float(ev.get("dur", 0.0)))
        elif kind == "compile":
            compiles.append(ev)
        elif kind == "gauge":
            gauges[ev["name"]] = ev.get("value")
        elif kind == "round":
            rounds.append(ev)
        elif kind == "counters":
            # periodic snapshot (per-round flush): monotonic, last wins —
            # a crashed run keeps its counters up to the last flush
            counters = ev.get("counters", {})
        elif kind == "summary":
            counters = ev.get("summary", {}).get("counters", counters)
        elif kind == "health_anomaly":
            health["anomalies"].append(ev)
        elif kind in ("health_rollback", "health_skip", "health_abort",
                      "health_anomaly_at_preempt"):
            health["resolutions"].append(ev)
        elif kind == "watchdog_stall":
            health["stalls"].append(ev)
        elif kind == "data_corrupt":
            health["data_corrupt"] += 1
        elif kind == "health_skip_batch":
            health["skipped_batches"] += 1
    # an anomaly is resolved by an inline resolution field (warn-only
    # metric events) or by any recovery event referencing its id
    resolved = {r.get("anomaly") for r in health["resolutions"]}
    health["unresolved"] = [
        a for a in health["anomalies"]
        if a.get("resolution") is None and a.get("id") not in resolved]
    out = {"spans": {}, "compiles": {}, "counters": counters,
           "gauges": gauges, "rounds": rounds, "health": health}
    for name, durs in spans.items():
        durs.sort()
        out["spans"][name] = {
            "count": len(durs),
            "total_s": round(sum(durs), 6),
            "p50_ms": round(1e3 * percentile(durs, 50), 4),
            "p90_ms": round(1e3 * percentile(durs, 90), 4),
            "p99_ms": round(1e3 * percentile(durs, 99), 4),
            "max_ms": round(1e3 * (durs[-1] if durs else 0.0), 4),
        }
    out["compiles"] = {
        "count": len(compiles),
        "total_s": round(sum(float(c.get("dur", 0.0)) for c in compiles), 6),
        "by_cause": count_by(compiles, "cause"),
    }
    return out


def print_report(agg, top=15):
    spans = agg["spans"]
    print("== top spans by total time ==")
    print("%-20s %8s %10s %9s %9s %9s %9s" %
          ("span", "count", "total_s", "p50_ms", "p90_ms", "p99_ms",
           "max_ms"))
    for name, a in sorted(spans.items(),
                          key=lambda kv: -kv[1]["total_s"])[:top]:
        print("%-20s %8d %10.3f %9.2f %9.2f %9.2f %9.2f" %
              (name, a["count"], a["total_s"], a["p50_ms"], a["p90_ms"],
               a["p99_ms"], a["max_ms"]))
    comp = agg["compiles"]
    print("\n== recompiles ==")
    print("count: %d   total: %.2fs" % (comp["count"], comp["total_s"]))
    for cause, n in sorted(comp["by_cause"].items()):
        print("  %-24s %d" % (cause, n))
    step = spans.get("train.step")
    if step:
        print("\n== step-time percentiles (train.step dispatch) ==")
        print("n=%d  p50=%.2fms  p90=%.2fms  p99=%.2fms  max=%.2fms" %
              (step["count"], step["p50_ms"], step["p90_ms"],
               step["p99_ms"], step["max_ms"]))
    if agg["rounds"]:
        print("\n== rounds ==")
        print("%6s %9s %12s %9s %9s %9s" %
              ("round", "images", "input_wait_s", "step_s", "eval_s",
               "ckpt_s"))
        for r in agg["rounds"]:
            print("%6d %9d %12.3f %9.3f %9.3f %9.3f" %
                  (r.get("round", -1), r.get("images", 0),
                   r.get("input_wait_s", 0.0), r.get("step_s", 0.0),
                   r.get("eval_s", 0.0), r.get("checkpoint_s", 0.0)))
    if agg["counters"]:
        print("\n== counters ==")
        for name, v in sorted(agg["counters"].items()):
            print("  %-28s %s" % (name, v))
    if agg["gauges"]:
        print("\n== gauges (last value) ==")
        for name, v in sorted(agg["gauges"].items()):
            print("  %-28s %s" % (name, v))
    h = agg.get("health", {})
    if h and (h["anomalies"] or h["stalls"] or h["data_corrupt"]
              or h["skipped_batches"]):
        print("\n== health ==")
        print("anomalies: %d  %s" %
              (len(h["anomalies"]),
               " ".join("%s=%d" % kv for kv in
                        sorted(count_by(h["anomalies"], "kind").items()))))
        if h["resolutions"]:
            print("resolutions: %d  %s" %
                  (len(h["resolutions"]),
                   " ".join("%s=%d" % kv for kv in sorted(
                       count_by(h["resolutions"], "ev").items()))))
        if h["stalls"]:
            print("watchdog stalls: %d  %s" %
                  (len(h["stalls"]),
                   " ".join("%s=%d" % kv for kv in sorted(
                       count_by(h["stalls"], "channel").items()))))
        if h["data_corrupt"]:
            print("corrupt data records: %d" % h["data_corrupt"])
        if h["skipped_batches"]:
            print("quarantined batches skipped: %d" % h["skipped_batches"])
        for a in h["unresolved"]:
            print("UNRESOLVED anomaly id=%s kind=%s round=%s batch=%s" %
                  (a.get("id"), a.get("kind"), a.get("round"),
                   a.get("batch")))


def main(argv):
    top = 15
    trace_out = None
    as_json = False
    paths = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--top" and i + 1 < len(argv):
            top = int(argv[i + 1])
            i += 2
        elif a == "--trace" and i + 1 < len(argv):
            trace_out = argv[i + 1]
            i += 2
        elif a == "--json":
            as_json = True
            i += 1
        elif a.startswith("--"):
            print("unknown option %s" % a, file=sys.stderr)
            return 1
        else:
            paths.append(a)
            i += 1
    if len(paths) != 1:
        print(__doc__, file=sys.stderr)
        return 1
    path = paths[0]
    if not os.path.exists(path):
        print("no such log: %s" % path, file=sys.stderr)
        return 1
    events = load_events(path)
    agg = aggregate(events)
    if as_json:
        print(json.dumps(agg, indent=1))
    else:
        print_report(agg, top=top)
    if trace_out:
        with open(trace_out, "w") as f:
            json.dump(events_to_chrome(events), f)
        print("\nchrome trace written to %s "
              "(open in chrome://tracing or ui.perfetto.dev)" % trace_out)
    unresolved = agg.get("health", {}).get("unresolved", [])
    if unresolved:
        print("%s: %d health_anomaly event(s) with no matching "
              "health_rollback/resolution — the run detected trouble and "
              "never recovered" % (path, len(unresolved)), file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
