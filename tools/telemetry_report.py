#!/usr/bin/env python
"""Summarize telemetry JSONL run logs (utils/telemetry.py).

Usage:
    python tools/telemetry_report.py run.jsonl [--top N] [--trace out.json]
                                               [--json] [--incidents]
    python tools/telemetry_report.py --merge shard0.jsonl shard1.jsonl ...
                                               [--top N] [--json]
    python tools/telemetry_report.py --fleet router.jsonl replica0.jsonl ...
                                               [--top N] [--json]

Prints top spans by total time, recompile count/causes/seconds, per-round
breakdowns, counters/gauges, fixed-bucket latency histograms (bucket table
+ p50/p90/p99), step-time percentiles, a training-health section
(anomalies/rollbacks/watchdog stalls/corrupt records, utils/health.py),
a serving section (shed rate, deadline-miss rate, circuit-breaker
transitions, per-request p50/p99 from the ``serve.request`` histogram,
utils/servd.py), a program-ledger section (the ``program_card`` events
utils/perf.py emits — per-compiled-program FLOPs / peak bytes /
compile time / roofline-predicted vs measured time, top programs by
compile cost and by roofline gap), and a request-breakdown section
(phase-attributed
p50/p99 over the ``serve_request_done`` events — queue_wait / dispatch /
prefill / decode / TTFT — plus the top-5 slowest requests with their
phase split and the requests that paid recompiles), and a
batch-scheduler section (per-bucket occupancy/waste reconstructed from
the transition-only ``batch_iteration`` events, admission-latency
percentiles, the ``serve.queue_age`` distribution, and the
``decode_convoy`` episode account — a log that ends with the convoy
latched is flagged unresolved).
An autopsy-breakdown section summarizes the slowdown verdicts the
serving processes stamp on ``serve_request_done`` /
``route_request_done`` events (utils/autopsy.py): per-cause attributed
seconds (p50/p99 across requests), the primary-verdict histogram, and
the top-5 primary verdicts; a conservation-laws section reports the
``books_broken`` transitions of the metrics auditor
(telemetry.BooksAuditor). ``--incidents`` additionally renders the
fleet incident timeline — every transition-only event stream (convoy,
KV pressure, SLO burn, outliers, breaker, scale/reload/drain, broken
books) merged into one wall-clock-ordered list, the offline twin of the
live ``/eventz`` endpoint.
``--trace`` additionally exports a chrome://tracing / Perfetto JSON built
from the span tree. ``--json`` emits the aggregate as one JSON object
instead of the table (for scripting).

``--merge`` reads one shard per process of a multihost run (the
``telemetry_log = run.%d.jsonl`` rank-placeholder layout): each shard's
timestamps are re-aligned onto the shared wall-clock epoch (the earliest
shard's ``t0_wall``), events keep their ``p`` process tag, histograms
merge EXACTLY (shared fixed buckets: bucket-count addition), counters sum
across processes, and the report adds a per-process breakdown — one
coherent cross-host view instead of N clobbering logs.

``--fleet`` merges a serving FLEET's logs — the router's
(``task = route``) plus its replicas' (``task = serve``) — which are
separate single-process runs that may all claim process index 0, so the
shards are relabeled by argument position (shard i -> process i) before
the same wall-clock re-basing. The report then JOINS the router's
``route_request_done`` events against the replicas'
``serve_request_done`` events on the shared trace id (the ``TRACE``
propagation of utils/routerd.py) and prints a per-hop breakdown: each
routed request's attempts/retries next to the phase split of every
replica that touched it, the router-overhead percentiles (router total
minus the slowest hop), and any ``fleet_outlier`` transitions.

Exit codes: 0 ok; 1 usage / unreadable file; 2 malformed log (a line
that is not valid JSON, or no telemetry events at all) OR a log with
``health_anomaly`` events that no resolution event (``health_rollback``
/ ``health_skip`` / ``health_abort`` referencing the anomaly id, or an
inline ``resolution`` field) ever answered, OR a log whose LAST
``serve_breaker`` event (per process) left the circuit breaker open,
OR a log whose LAST ``slo_burn`` event (per process) left the SLO
error budget burning (state 1), OR a log whose LAST ``books_broken``
event (per process and law) left a conservation law latched broken —
CI gates on this so neither a broken emitter, an unrecovered training
anomaly, a serving run that ended with its backend shedding, one that
ended blowing its SLOs, nor one whose metrics books stopped reconciling
can silently pass.
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".."))

from cxxnet_tpu.utils import autopsy  # noqa: E402
from cxxnet_tpu.utils.perf import MEASURED_SERIES  # noqa: E402
from cxxnet_tpu.utils.telemetry import (  # noqa: E402
    HIST_BUCKETS, Histogram, count_by, events_to_chrome, fmt_ms,
    percentile)


def load_events(path):
    """Parse one-event-per-line JSONL; malformed lines are fatal (exit 2:
    the log writer is append-only, so a bad line means a broken emitter
    or a truncated copy — summarizing around it would lie)."""
    events = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError as e:
                print("%s:%d: malformed JSONL line: %s"
                      % (path, lineno, e), file=sys.stderr)
                sys.exit(2)
            if not isinstance(ev, dict):
                print("%s:%d: event is not a JSON object" % (path, lineno),
                      file=sys.stderr)
                sys.exit(2)
            events.append(ev)
    if not events:
        print("%s: no telemetry events" % path, file=sys.stderr)
        sys.exit(2)
    return events


def shard_identity(events, default_p):
    """(t0_wall, process_index) of one shard: the meta event carries the
    wall-clock epoch; the process tag rides on every event ("p").
    t0_wall is None when no meta event exists (truncated copy)."""
    t0 = None
    p = None
    for ev in events:
        if t0 is None and ev.get("ev") == "meta":
            t0 = float(ev.get("t0_wall", 0.0))
        if p is None and "p" in ev:
            p = int(ev["p"])
        if t0 is not None and p is not None:
            break
    return t0, (p if p is not None else default_p)


def merge_shards(shard_events):
    """Merge per-process shards into ONE event stream on a shared clock.

    Each shard's ``ts`` values are seconds since ITS OWN start; shards of
    one run started at (slightly) different wall times. Re-base every
    shard onto the earliest ``t0_wall`` so "the same moment" has the same
    ts across processes, tag untagged events with the shard's process
    index, and sort. Duplicate process indices (merging the same shard
    twice) are rejected — the aggregate would double-count."""
    metas = []
    for i, events in enumerate(shard_events):
        t0, p = shard_identity(events, i)
        if t0 is None:
            # no meta event = no epoch: re-basing the OTHER shards
            # against a 0.0 epoch would shift them by ~50 years —
            # refuse rather than emit a silently garbage timeline
            print("--merge: shard %d has no 'meta' event (truncated "
                  "copy?); cannot align it on the shared wall-clock "
                  "epoch" % i, file=sys.stderr)
            sys.exit(2)
        metas.append((t0, p, events))
    seen = {}
    for i, (_, p, _) in enumerate(metas):
        if p in seen:
            print("--merge: shards %d and %d both claim process index %d "
                  "— merging the same shard twice?" % (seen[p], i, p),
                  file=sys.stderr)
            sys.exit(1)
        seen[p] = i
    epoch = min(t0 for t0, _, _ in metas)
    merged = []
    for t0, p, events in metas:
        off = t0 - epoch
        for ev in events:
            ev = dict(ev)
            if "ts" in ev:
                ev["ts"] = round(float(ev["ts"]) + off, 6)
            ev.setdefault("p", p)
            merged.append(ev)
    merged.sort(key=lambda e: e.get("ts", 0.0))
    return merged


def merge_fleet_shards(shard_events):
    """--fleet: the router log + N replica logs are DIFFERENT processes
    that may each carry process index 0 (every one is its own
    single-process run), so --merge's duplicate-index guard would
    reject them. Relabel shard i as process i — argument order is the
    identity (put the router first by convention) — then re-base on
    the shared wall-clock epoch exactly like --merge."""
    relabeled = []
    for i, events in enumerate(shard_events):
        relabeled.append([dict(ev, p=i) for ev in events])
    return merge_shards(relabeled)


def aggregate(events):
    spans = {}
    compiles = []
    program_compiles = []
    counters_by_p = {}
    hists_by_p = {}
    gauges = {}
    gauges_by_p = {}
    rounds = []
    procs = set()
    by_proc = {}
    health = {"anomalies": [], "resolutions": [], "stalls": [],
              "data_corrupt": 0, "skipped_batches": 0}
    breaker_events = []
    requests = []
    route_requests = []
    outlier_events = []
    slo_events = []
    program_cards = {}
    batch_events = []
    convoy_events = []
    books_events = []

    def proc(ev):
        p = int(ev.get("p", 0))
        procs.add(p)
        return p

    for ev in events:
        kind = ev.get("ev")
        if kind == "span":
            a = spans.setdefault(ev["name"], [])
            a.append(float(ev.get("dur", 0.0)))
            pb = by_proc.setdefault(proc(ev), {"spans": {}, "images": 0,
                                               "rounds": 0})
            sp = pb["spans"].setdefault(ev["name"], [0, 0.0])
            sp[0] += 1
            sp[1] += float(ev.get("dur", 0.0))
        elif kind == "compile":
            compiles.append(ev)
            proc(ev)
        elif kind == "program_compile":
            # the perf ledger's compile flight record (one per program
            # the warm grid learns about): carries the readiness climb
            program_compiles.append(ev)
            proc(ev)
        elif kind == "gauge":
            gauges[ev["name"]] = ev.get("value")
            gauges_by_p.setdefault(proc(ev), {})[ev["name"]] = \
                ev.get("value")
        elif kind == "round":
            rounds.append(ev)
            pb = by_proc.setdefault(proc(ev), {"spans": {}, "images": 0,
                                               "rounds": 0})
            pb["images"] += int(ev.get("images", 0))
            pb["rounds"] += 1
        elif kind == "counters":
            # periodic snapshot (per-round flush): monotonic, last wins
            # PER PROCESS — a crashed shard keeps its counters to the
            # last flush; cross-process totals are summed below
            counters_by_p[proc(ev)] = ev.get("counters", {})
        elif kind == "hists":
            # cumulative like counters: last snapshot per process wins
            hists_by_p[proc(ev)] = ev.get("hists", {})
        elif kind == "summary":
            p = proc(ev)
            s = ev.get("summary", {})
            counters_by_p[p] = s.get("counters", counters_by_p.get(p, {}))
        elif kind == "health_anomaly":
            health["anomalies"].append(ev)
        elif kind in ("health_rollback", "health_skip", "health_abort",
                      "health_anomaly_at_preempt"):
            health["resolutions"].append(ev)
        elif kind == "watchdog_stall":
            health["stalls"].append(ev)
        elif kind == "data_corrupt":
            health["data_corrupt"] += 1
        elif kind == "health_skip_batch":
            health["skipped_batches"] += 1
        elif kind == "serve_breaker":
            breaker_events.append(ev)
            proc(ev)
        elif kind == "serve_request_done":
            requests.append(ev)
            proc(ev)
        elif kind == "route_request_done":
            route_requests.append(ev)
            proc(ev)
        elif kind == "fleet_outlier":
            outlier_events.append(ev)
            proc(ev)
        elif kind == "slo_burn":
            slo_events.append(ev)
            proc(ev)
        elif kind == "batch_iteration":
            batch_events.append(ev)
            proc(ev)
        elif kind == "decode_convoy":
            convoy_events.append(ev)
            proc(ev)
        elif kind == "books_broken":
            books_events.append(ev)
            proc(ev)
        elif kind == "program_card":
            # the performance ledger's per-compiled-program card
            # (utils/perf.py): last event per (process, name, shapes
            # signature) wins — re-completions carry cumulative
            # compile counts
            program_cards[(proc(ev), ev.get("name"),
                           ev.get("sig"))] = ev
    # an anomaly is resolved by an inline resolution field (warn-only
    # metric events) or by any recovery event referencing its id —
    # matched PER PROCESS: anomaly ids are per-process counters, so in a
    # merged multihost report shard A's rollback of id=1 must not
    # resolve shard B's unrelated (and possibly unrecovered) id=1
    resolved = {(int(r.get("p", 0)), r.get("anomaly"))
                for r in health["resolutions"]}
    health["unresolved"] = [
        a for a in health["anomalies"]
        if a.get("resolution") is None
        and (int(a.get("p", 0)), a.get("id")) not in resolved]
    counters = {}
    for snap in counters_by_p.values():
        for name, v in snap.items():
            counters[name] = counters.get(name, 0) + v
    # exact cross-shard histogram merge: every histogram shares the fixed
    # log-spaced HIST_BUCKETS, so merging is bucket-count addition
    merged_hists = {}
    for p, snap in hists_by_p.items():
        for name, d in snap.items():
            try:
                merged_hists.setdefault(name, Histogram()).merge_dict(d)
            except (ValueError, TypeError) as e:
                print("process %d histogram %r: %s" % (p, name, e),
                      file=sys.stderr)
                sys.exit(2)
    # serving summary: rates off the (summed) counters, breaker
    # transition counts, and the FINAL breaker state per process — a log
    # that ends breaker-open is an unresolved serving outage (exit 2)
    serving = None
    if breaker_events or any(k.startswith("serve.") for k in counters):
        acc = counters.get("serve.accepted", 0)
        serving = {
            "accepted": acc,
            "served": counters.get("serve.requests", 0),
            "errors": counters.get("serve.errors", 0),
            "shed": counters.get("serve.shed", 0),
            "deadline": counters.get("serve.deadline", 0),
            "shed_rate": round(counters.get("serve.shed", 0)
                               / float(acc), 4) if acc else 0.0,
            "deadline_miss_rate": round(counters.get("serve.deadline", 0)
                                        / float(acc), 4) if acc else 0.0,
            "reloads": counters.get("serve.reloads", 0),
            "breaker_transitions": count_by(breaker_events, "state"),
            "breaker_final": {},
        }
        for ev in breaker_events:       # events arrive time-sorted
            serving["breaker_final"][str(int(ev.get("p", 0)))] = \
                ev.get("state")
        serving["breaker_open_unresolved"] = sorted(
            p for p, st in serving["breaker_final"].items()
            if st == "open")
    # request breakdown: phase-attributed percentiles over the
    # serve_request_done events, the slowest requests with their phase
    # split, and recompile attribution (from the events' recompile count
    # plus any compile events tagged with a request id)
    req_agg = None
    if requests:
        phases = {}
        for ph in ("queue_wait", "dispatch", "prefill", "decode",
                   "ttft", "total"):
            vals = sorted(float(r[ph + "_s"]) for r in requests
                          if r.get(ph + "_s") is not None)
            if vals:
                phases[ph] = {
                    "count": len(vals),
                    "p50_ms": round(1e3 * percentile(vals, 50), 4),
                    "p99_ms": round(1e3 * percentile(vals, 99), 4),
                    "max_ms": round(1e3 * vals[-1], 4)}
        slowest = sorted(requests,
                         key=lambda r: -float(r.get("total_s", 0.0)))[:5]
        recomp = {}
        for r in requests:
            if r.get("recompiles"):
                recomp[str(r.get("req"))] = int(r["recompiles"])
        for c in compiles:
            if "req" in c:
                recomp.setdefault(str(c["req"]), 0)
                recomp[str(c["req"])] = max(recomp[str(c["req"])], 1)
        req_agg = {
            "count": len(requests),
            "outcomes": count_by(requests, "outcome"),
            "phases": phases,
            "slowest": [{
                "req": r.get("req"), "outcome": r.get("outcome"),
                "total_s": r.get("total_s"),
                "tokens": r.get("tokens", 0),
                "phases": {ph: r.get(ph + "_s")
                           for ph in ("queue_wait", "dispatch",
                                      "prefill", "decode")}}
                for r in slowest],
            "recompile_requests": dict(sorted(recomp.items())),
        }
    # fleet view: the router's route_request_done events joined against
    # the replicas' serve_request_done events on the shared trace id —
    # one id names a request on every process that touched it (--fleet)
    fleet = None
    if route_requests:
        by_req = {}
        for r in requests:
            by_req.setdefault(str(r.get("req")), []).append(r)
        joined = []
        overheads = []
        for ev in route_requests:
            rid = str(ev.get("req"))
            hops = [{"p": int(h.get("p", 0)),
                     "outcome": h.get("outcome"),
                     "total_s": h.get("total_s"),
                     "ttft_s": h.get("ttft_s"),
                     "queue_wait_s": h.get("queue_wait_s"),
                     "prefill_s": h.get("prefill_s"),
                     "decode_s": h.get("decode_s")}
                    for h in by_req.get(rid, [])]
            row = {"req": rid, "outcome": ev.get("outcome"),
                   "total_s": ev.get("total_s"),
                   "attempts": int(ev.get("attempts", 0)),
                   "retries": int(ev.get("retries", 0)),
                   "replicas": ev.get("replicas") or [],
                   "hops": hops}
            if ev.get("total_s") is not None and hops:
                hop_tot = max(float(h.get("total_s") or 0.0)
                              for h in hops)
                # router total minus the slowest hop's total = queueing
                # + connect + rewrite + relay overhead the router added
                overheads.append(max(0.0, float(ev["total_s"])
                                     - hop_tot))
            joined.append(row)
        overheads.sort()
        fleet = {
            "requests": len(route_requests),
            "outcomes": count_by(route_requests, "outcome"),
            "retried": sum(1 for ev in route_requests
                           if int(ev.get("retries", 0)) > 0),
            "matched": sum(1 for j in joined if j["hops"]),
            "unmatched": sum(1 for j in joined if not j["hops"]),
            "router_overhead_p50_ms":
                round(1e3 * percentile(overheads, 50), 4)
                if overheads else None,
            "router_overhead_p99_ms":
                round(1e3 * percentile(overheads, 99), 4)
                if overheads else None,
            "slowest": sorted(joined,
                              key=lambda j: -float(j.get("total_s")
                                                   or 0.0))[:5],
            "outlier_transitions": [
                {"replica": ev.get("replica"),
                 "outlier": int(ev.get("outlier", 0)),
                 "p99_ms": ev.get("p99_ms"),
                 "fleet_p99_ms": ev.get("fleet_p99_ms")}
                for ev in outlier_events],
        }
    # SLO burn account: transition events only — the LAST state per
    # process is the gate (a log that ends burning exits 2)
    slo = None
    if slo_events:
        final = {}
        for ev in slo_events:           # events arrive time-sorted
            final[str(int(ev.get("p", 0)))] = ev
        slo = {"transitions": len(slo_events),
               "final": {p: {"state": int(ev.get("state", 0)),
                             "burn_rate": ev.get("burn_rate")}
                         for p, ev in final.items()},
               "burning": sorted(p for p, ev in final.items()
                                 if int(ev.get("state", 0)))}
    # autopsy breakdown: the slowdown verdicts the serving processes
    # stamp on their done events (utils/autopsy.py) — per-cause
    # attributed seconds and the primary-verdict histogram
    auts = [ev["autopsy"] for ev in requests + route_requests
            if isinstance(ev.get("autopsy"), dict)]
    autopsy_agg = None
    if auts:
        cause_vals = {}
        for a in auts:
            for c, s in (a.get("causes") or {}).items():
                cause_vals.setdefault(c, []).append(float(s))
        cause_stats = {}
        for c, vals in sorted(cause_vals.items()):
            vals.sort()
            cause_stats[c] = {
                "requests": sum(1 for v in vals if v > 0),
                "total_s": round(sum(vals), 6),
                "p50_ms": round(1e3 * percentile(vals, 50), 4),
                "p99_ms": round(1e3 * percentile(vals, 99), 4)}
        prim = count_by(auts, "primary")
        autopsy_agg = {
            "count": len(auts),
            "causes": cause_stats,
            "primary": prim,
            "top_primary": sorted(prim.items(),
                                  key=lambda kv: (-kv[1], kv[0]))[:5]}
    # conservation laws: books_broken transitions (telemetry
    # BooksAuditor) — the LAST state per (process, law) is the gate; a
    # log that ends with any law latched broken exits 2, because every
    # other number in this report is then suspect
    books = None
    if books_events:
        final_bk = {}
        for ev in books_events:         # events arrive time-sorted
            final_bk[(int(ev.get("p", 0)), str(ev.get("law")))] = ev
        books = {
            "transitions": len(books_events),
            "final": {"p%d:%s" % k: int(ev.get("broken", 0))
                      for k, ev in sorted(final_bk.items())},
            "details": {"p%d:%s" % k: ev.get("detail")
                        for k, ev in sorted(final_bk.items())
                        if ev.get("detail")},
            "latched": sorted("p%d:%s" % k
                              for k, ev in final_bk.items()
                              if int(ev.get("broken", 0)))}
    # batch scheduler: per-bucket occupancy/waste from the
    # batch_iteration events (transition-only — one event per
    # composition CHANGE). Reconstruction is exact: the event at
    # iteration N stepped at ``occupancy`` and left ``occupancy_after``
    # aboard (its own turn's retirements excluded), and NOTHING changes
    # until the next event — so N itself weighs ``occupancy`` and
    # N+1..next-event-1 weigh ``occupancy_after``. Non-stepped flush
    # events (a turn whose admissions all finished at prefill) carry
    # admissions/retirements but no decode pass, so they stay out of
    # the occupancy weighting. Plus admission-latency percentiles from
    # the requests' queue_wait, the queue-age distribution, and the
    # decode_convoy episode account (a log that ENDS with the convoy
    # latched is reported as unresolved, the breaker-open discipline)
    batch = None
    if batch_events or convoy_events:
        by_bucket = {}
        by_pe = {}
        for ev in batch_events:
            by_pe.setdefault(int(ev.get("p", 0)), []).append(ev)

        def bucket_of(ev):
            return by_bucket.setdefault(int(ev.get("bucket") or 0), {
                "iterations": 0, "slot_iterations": 0,
                "admitted": 0, "retired": 0, "errors": 0})

        for p, evs in by_pe.items():
            evs.sort(key=lambda e: int(e.get("iter", 0)))
            for ev in evs:
                d = bucket_of(ev)
                d["admitted"] += len(ev.get("admitted") or [])
                d["retired"] += len(ev.get("retired") or [])
                if ev.get("error"):
                    d["errors"] += 1
            stepped = [e for e in evs if e.get("stepped", 1)]
            for k, ev in enumerate(stepped):
                gap = 1
                if k + 1 < len(stepped) \
                        and stepped[k + 1].get("bucket") \
                        == ev.get("bucket"):
                    gap = max(1, int(stepped[k + 1].get("iter", 0))
                              - int(ev.get("iter", 0)))
                d = bucket_of(ev)
                occ = int(ev.get("occupancy", 0))
                after = ev.get("occupancy_after")
                after = occ if after is None else int(after)
                d["iterations"] += gap
                d["slot_iterations"] += occ + after * (gap - 1)
        for b, d in by_bucket.items():
            occ = (d["slot_iterations"] / float(d["iterations"])
                   if d["iterations"] else None)
            d["mean_occupancy"] = round(occ, 3) if occ is not None \
                else None
            d["waste_pct"] = round(100.0 * (1.0 - occ / b), 2) \
                if occ is not None and b else None
        qwaits = sorted(float(r["queue_wait_s"]) for r in requests
                        if r.get("queue_wait_s") is not None)
        convoy_final = {}
        for ev in convoy_events:        # events arrive time-sorted
            convoy_final[str(int(ev.get("p", 0)))] = \
                int(ev.get("convoy", 0))
        batch = {
            "events": len(batch_events),
            "buckets": {str(b): d for b, d
                        in sorted(by_bucket.items())},
            "admission_p50_ms":
                round(1e3 * percentile(qwaits, 50), 4)
                if qwaits else None,
            "admission_p99_ms":
                round(1e3 * percentile(qwaits, 99), 4)
                if qwaits else None,
            "convoy_episodes": sum(1 for ev in convoy_events
                                   if int(ev.get("convoy", 0))),
            "convoys": [
                {"p": int(ev.get("p", 0)),
                 "pinned": ev.get("pinned"),
                 "bucket": ev.get("bucket"),
                 "age_iters": ev.get("age_iters"),
                 "queue_depth": ev.get("queue_depth")}
                for ev in convoy_events
                if int(ev.get("convoy", 0))],
            "convoy_unresolved": sorted(
                p for p, st in convoy_final.items() if st),
        }
    # program ledger: one row per carded program (utils/perf.py),
    # joined against the measured latency histograms like the live
    # /programz table — MFU% and roofline efficiency from the log alone
    programs = None
    if program_cards:
        rows = []
        for (p, name, sig), ev in sorted(
                program_cards.items(), key=lambda kv: str(kv[0])):
            series = MEASURED_SERIES.get(name)
            h = merged_hists.get(series) if series else None
            st = h.stats() if h is not None and h.n else None
            row = {"p": p, "name": name, "shapes": ev.get("shapes"),
                   "spec": ev.get("spec"), "cause": ev.get("cause"),
                   "compiles": int(ev.get("compiles") or 0),
                   "compile_s": float(ev.get("compile_s") or 0.0),
                   "flops": ev.get("flops"),
                   "peak_bytes": ev.get("peak_bytes"),
                   "predicted_s": ev.get("predicted_s"),
                   "status": ev.get("status"), "error": ev.get("error"),
                   "measured_p50_ms": st["p50_ms"] if st else None,
                   "measured_p99_ms": st["p99_ms"] if st else None,
                   "mfu_pct": None, "roofline_eff_pct": None}
            if st and st["p50_ms"]:
                p50_s = st["p50_ms"] / 1e3
                peak = ev.get("spec_peak_flops")
                if row["flops"] is not None and peak:
                    row["mfu_pct"] = round(
                        100.0 * row["flops"] / (p50_s * peak), 2)
                if row["predicted_s"] is not None:
                    row["roofline_eff_pct"] = round(
                        100.0 * row["predicted_s"] / p50_s, 2)
            rows.append(row)
        gapped = [r for r in rows
                  if r["roofline_eff_pct"] is not None]
        programs = {
            "count": len(rows),
            "cards": rows,
            "compile_s": round(sum(r["compile_s"] for r in rows), 6),
            "hbm_peak_bytes": max(
                (r["peak_bytes"] for r in rows
                 if r["peak_bytes"] is not None), default=None),
            "top_by_compile": [r["name"] for r in sorted(
                rows, key=lambda r: -r["compile_s"])[:5]],
            # the roofline GAP ranking: lowest efficiency = furthest
            # from what the hardware allows
            "top_by_gap": [r["name"] for r in sorted(
                gapped, key=lambda r: r["roofline_eff_pct"])[:5]],
        }
    out = {"spans": {}, "compiles": {}, "counters": counters,
           "gauges": gauges, "rounds": rounds, "health": health,
           "serving": serving, "requests": req_agg, "fleet": fleet,
           "slo": slo, "programs": programs, "batch": batch,
           "autopsy": autopsy_agg, "books": books,
           "hists": {}}
    for name, h in sorted(merged_hists.items()):
        st = h.stats()
        st["buckets"] = h.to_dict()["buckets"]
        out["hists"][name] = st
    for name, durs in spans.items():
        durs.sort()
        out["spans"][name] = {
            "count": len(durs),
            "total_s": round(sum(durs), 6),
            "p50_ms": round(1e3 * percentile(durs, 50), 4),
            "p90_ms": round(1e3 * percentile(durs, 90), 4),
            "p99_ms": round(1e3 * percentile(durs, 99), 4),
            "max_ms": round(1e3 * (durs[-1] if durs else 0.0), 4),
        }
    out["compiles"] = {
        "count": len(compiles),
        "total_s": round(sum(float(c.get("dur", 0.0)) for c in compiles), 6),
        "by_cause": count_by(compiles, "cause"),
    }
    if program_compiles:
        # the compile-cliff section (doc/performance.md "Compile
        # cliff"): the warm-grid readiness climb across the run plus
        # the requests that paid a cliff in-band — events arrive in
        # emission order, so first/last bracket the climb
        pc = program_compiles
        out["compile_cliff"] = {
            "count": len(pc),
            "total_s": round(sum(float(c.get("seconds") or 0.0)
                                 for c in pc), 6),
            "ready_pct_first": pc[0].get("ready_pct"),
            "ready_pct_last": pc[-1].get("ready_pct"),
            "by_name": count_by(pc, "name"),
            "stalled_requests": sorted(
                {str(c["req"]) for c in pc if c.get("req")}),
        }
    if len(procs) > 1:
        out["processes"] = {}
        for p in sorted(procs):
            pb = by_proc.get(p, {"spans": {}, "images": 0, "rounds": 0})
            out["processes"][str(p)] = {
                "images": pb["images"],
                "rounds": pb["rounds"],
                "spans": {name: {"count": n, "total_s": round(t, 6)}
                          for name, (n, t) in sorted(pb["spans"].items())},
                "counters": counters_by_p.get(p, {}),
                # per-process gauge values: the merged top-level dict is
                # last-event-wins across shards, which would hide e.g.
                # the one near-OOM host's device.bytes_in_use
                "gauges": gauges_by_p.get(p, {}),
            }
    return out


# empty-histogram stats carry None percentiles (a series that never
# fired); the shared renderer turns them into "n/a", never garbage zeros
_fmt_ms = fmt_ms


def _bucket_rows(buckets):
    """(le, cumulative_count) rows of a sparse bucket dict — CUMULATIVE,
    matching Prometheus ``le`` semantics (and /metrics output): the row
    for bound B counts every sample <= B. One row per occupied bound."""
    rows = []
    cum = 0
    for i, c in sorted(((int(i), c) for i, c in buckets.items())):
        cum += c
        le = "+Inf" if i >= len(HIST_BUCKETS) else "%g" % HIST_BUCKETS[i]
        rows.append((le, cum))
    return rows


def print_report(agg, top=15):
    spans = agg["spans"]
    print("== top spans by total time ==")
    print("%-20s %8s %10s %9s %9s %9s %9s" %
          ("span", "count", "total_s", "p50_ms", "p90_ms", "p99_ms",
           "max_ms"))
    for name, a in sorted(spans.items(),
                          key=lambda kv: -kv[1]["total_s"])[:top]:
        print("%-20s %8d %10.3f %9.2f %9.2f %9.2f %9.2f" %
              (name, a["count"], a["total_s"], a["p50_ms"], a["p90_ms"],
               a["p99_ms"], a["max_ms"]))
    comp = agg["compiles"]
    print("\n== recompiles ==")
    print("count: %d   total: %.2fs" % (comp["count"], comp["total_s"]))
    for cause, n in sorted(comp["by_cause"].items()):
        print("  %-24s %d" % (cause, n))
    cliff = agg.get("compile_cliff")
    if cliff:
        print("\n== compile cliff (warm-grid readiness climb) ==")
        print("programs: %d   total: %.2fs   ready: %s%% -> %s%%"
              % (cliff["count"], cliff["total_s"],
                 "?" if cliff["ready_pct_first"] is None
                 else cliff["ready_pct_first"],
                 "?" if cliff["ready_pct_last"] is None
                 else cliff["ready_pct_last"]))
        for name, n in sorted(cliff["by_name"].items()):
            print("  %-24s %d" % (name, n))
        if cliff["stalled_requests"]:
            print("  stalled requests: %s"
                  % ", ".join(cliff["stalled_requests"][:16]))
    step = spans.get("train.step")
    if step:
        print("\n== step-time percentiles (train.step dispatch) ==")
        print("n=%d  p50=%.2fms  p90=%.2fms  p99=%.2fms  max=%.2fms" %
              (step["count"], step["p50_ms"], step["p90_ms"],
               step["p99_ms"], step["max_ms"]))
    if agg.get("hists"):
        print("\n== latency histograms (fixed log-spaced buckets, "
              "merge-exact) ==")
        for name, h in sorted(agg["hists"].items(),
                              key=lambda kv: -kv[1]["sum_s"]):
            print("%-24s n=%-8d sum=%.3fs  p50=%s  p90=%s  p99=%s"
                  % (name, h["count"], h["sum_s"], _fmt_ms(h["p50_ms"]),
                     _fmt_ms(h["p90_ms"]), _fmt_ms(h["p99_ms"])))
            for le, c in _bucket_rows(h.get("buckets", {})):
                print("    le=%-12s %d" % (le, c))
    if agg["rounds"]:
        print("\n== rounds ==")
        multi = "processes" in agg
        pre_hdr = "%6s " % "proc" if multi else ""
        print(pre_hdr + "%6s %9s %12s %9s %9s %9s" %
              ("round", "images", "input_wait_s", "step_s", "eval_s",
               "ckpt_s"))
        for r in agg["rounds"]:
            pre = "%6d " % r.get("p", 0) if multi else ""
            print(pre + "%6d %9d %12.3f %9.3f %9.3f %9.3f" %
                  (r.get("round", -1), r.get("images", 0),
                   r.get("input_wait_s", 0.0), r.get("step_s", 0.0),
                   r.get("eval_s", 0.0), r.get("checkpoint_s", 0.0)))
    if agg["counters"]:
        print("\n== counters%s ==" %
              (" (summed across processes)" if "processes" in agg else ""))
        for name, v in sorted(agg["counters"].items()):
            print("  %-28s %s" % (name, v))
    if agg["gauges"]:
        print("\n== gauges (last value) ==")
        for name, v in sorted(agg["gauges"].items()):
            print("  %-28s %s" % (name, v))
    if "processes" in agg:
        print("\n== per-process breakdown ==")
        for p, pb in sorted(agg["processes"].items(), key=lambda kv:
                            int(kv[0])):
            print("process %s: %d rounds, %d images" %
                  (p, pb["rounds"], pb["images"]))
            ranked = sorted(pb["spans"].items(),
                            key=lambda kv: -kv[1]["total_s"])[:5]
            for name, a in ranked:
                print("    %-20s %8d calls %10.3fs" %
                      (name, a["count"], a["total_s"]))
            for name, v in sorted(pb.get("counters", {}).items()):
                print("    counter %-20s %s" % (name, v))
            for name, v in sorted(pb.get("gauges", {}).items()):
                print("    gauge   %-20s %s" % (name, v))
    sv = agg.get("serving")
    if sv:
        print("\n== serving ==")
        print("accepted: %d  served: %d  errors: %d  shed: %d "
              "(rate %.2f%%)  deadline-missed: %d (rate %.2f%%)"
              % (sv["accepted"], sv["served"], sv["errors"], sv["shed"],
                 100 * sv["shed_rate"], sv["deadline"],
                 100 * sv["deadline_miss_rate"]))
        req = agg.get("hists", {}).get("serve.request")
        if req:
            print("request latency: n=%d  p50=%s  p90=%s  p99=%s"
                  % (req["count"], _fmt_ms(req["p50_ms"]),
                     _fmt_ms(req["p90_ms"]), _fmt_ms(req["p99_ms"])))
        if sv["reloads"]:
            print("model reloads: %d" % sv["reloads"])
        if sv["breaker_transitions"]:
            print("breaker transitions: %s" %
                  " ".join("%s=%d" % kv for kv in
                           sorted(sv["breaker_transitions"].items())))
            for p, st in sorted(sv["breaker_final"].items()):
                print("  process %s final breaker state: %s%s"
                      % (p, st, "  UNRESOLVED" if st == "open" else ""))
    rq = agg.get("requests")
    if rq:
        print("\n== request breakdown (phase-attributed) ==")
        print("requests: %d  %s"
              % (rq["count"],
                 " ".join("%s=%d" % kv
                          for kv in sorted(rq["outcomes"].items()))))
        print("%-12s %8s %10s %10s %10s" %
              ("phase", "count", "p50_ms", "p99_ms", "max_ms"))
        for ph in ("queue_wait", "dispatch", "prefill", "decode",
                   "ttft", "total"):
            a = rq["phases"].get(ph)
            if a:
                print("%-12s %8d %10.2f %10.2f %10.2f" %
                      (ph, a["count"], a["p50_ms"], a["p99_ms"],
                       a["max_ms"]))
        print("top-5 slowest requests:")
        for r in rq["slowest"]:
            ph = r["phases"]
            print("  req=%-8s %-14s total=%8.2fms  queue=%.2f "
                  "dispatch=%.2f prefill=%.2f decode=%.2f  tokens=%d"
                  % (r["req"], r["outcome"],
                     1e3 * float(r.get("total_s") or 0.0),
                     *(1e3 * float(ph.get(k) or 0.0)
                       for k in ("queue_wait", "dispatch", "prefill",
                                 "decode")), r.get("tokens", 0)))
        if rq["recompile_requests"]:
            print("recompile-attributed requests: %s"
                  % " ".join("req=%s(%d)" % kv for kv in
                             rq["recompile_requests"].items()))
    au = agg.get("autopsy")
    if au:
        print("\n== autopsy breakdown (slowdown verdicts) ==")
        print("requests with verdicts: %d" % au["count"])
        print("%-16s %9s %10s %10s %10s" %
              ("cause", "requests", "total_s", "p50_ms", "p99_ms"))
        for c in autopsy.CAUSES:
            st = au["causes"].get(c)
            if st:
                print("%-16s %9d %10.3f %10.2f %10.2f" %
                      (c, st["requests"], st["total_s"],
                       st["p50_ms"], st["p99_ms"]))
        print("top primary verdicts: %s"
              % "  ".join("%s(%d)" % (c, n)
                          for c, n in au["top_primary"]))
    bt = agg.get("batch")
    if bt:
        print("\n== batch scheduler (iteration-level decode "
              "datapath) ==")
        if bt["buckets"]:
            print("%-8s %12s %10s %9s %9s %7s" %
                  ("bucket", "iterations", "mean_occ", "waste%",
                   "admitted", "errors"))
            for b, d in sorted(bt["buckets"].items(),
                               key=lambda kv: int(kv[0])):
                print("%-8s %12d %10s %9s %9d %7d" %
                      (b, d["iterations"],
                       "n/a" if d["mean_occupancy"] is None
                       else "%.2f" % d["mean_occupancy"],
                       "n/a" if d["waste_pct"] is None
                       else "%.1f" % d["waste_pct"],
                       d["admitted"], d["errors"]))
        if bt["admission_p99_ms"] is not None:
            print("admission latency (queue_wait): p50=%s  p99=%s"
                  % (_fmt_ms(bt["admission_p50_ms"]),
                     _fmt_ms(bt["admission_p99_ms"])))
        qa = agg.get("hists", {}).get("serve.queue_age")
        if qa and qa.get("count"):
            print("queue age at iteration: n=%d  p50=%s  p99=%s"
                  % (qa["count"], _fmt_ms(qa["p50_ms"]),
                     _fmt_ms(qa["p99_ms"])))
        print("convoy episodes: %d%s"
              % (bt["convoy_episodes"],
                 "  UNRESOLVED on process(es) %s (log ends with a "
                 "straggler pinning a full bucket)"
                 % ",".join(bt["convoy_unresolved"])
                 if bt["convoy_unresolved"] else ""))
        for c in bt["convoys"]:
            print("  p=%-3d pinned=%-10s bucket=%s age=%s iters  "
                  "queue_depth=%s"
                  % (c["p"], c.get("pinned"), c.get("bucket"),
                     c.get("age_iters"), c.get("queue_depth")))
    fl = agg.get("fleet")
    if fl:
        print("\n== fleet requests (router <-> replica join on "
              "trace id) ==")
        print("routed: %d  %s  retried: %d  hop-matched: %d"
              "  unmatched: %d"
              % (fl["requests"],
                 " ".join("%s=%d" % kv
                          for kv in sorted(fl["outcomes"].items())),
                 fl["retried"], fl["matched"], fl["unmatched"]))
        if fl["router_overhead_p50_ms"] is not None:
            print("router overhead (total - slowest hop): p50=%s  "
                  "p99=%s"
                  % (_fmt_ms(fl["router_overhead_p50_ms"]),
                     _fmt_ms(fl["router_overhead_p99_ms"])))
        print("top-5 slowest routed requests (per-hop breakdown):")
        for j in fl["slowest"]:
            print("  req=%-18s %-10s total=%8.2fms  attempts=%d"
                  "%s  via %s"
                  % (j["req"], j["outcome"],
                     1e3 * float(j.get("total_s") or 0.0),
                     j["attempts"],
                     " retries=%d" % j["retries"] if j["retries"]
                     else "",
                     ",".join(j["replicas"]) or "-"))
            for h in j["hops"]:
                print("    hop p=%-3d %-12s total=%s ttft=%s "
                      "queue=%s prefill=%s decode=%s"
                      % (h["p"], h.get("outcome"),
                         *(_fmt_ms(None if h.get(k) is None
                                   else 1e3 * float(h[k]))
                           for k in ("total_s", "ttft_s",
                                     "queue_wait_s", "prefill_s",
                                     "decode_s"))))
        if fl["outlier_transitions"]:
            print("outlier transitions:")
            for t in fl["outlier_transitions"]:
                print("  %-21s -> %s (p99 %s vs fleet %s)"
                      % (t["replica"],
                         "OUTLIER" if t["outlier"] else "ok",
                         _fmt_ms(t.get("p99_ms")),
                         _fmt_ms(t.get("fleet_p99_ms"))))
    slo = agg.get("slo")
    if slo:
        print("\n== slo ==")
        print("burn transitions: %d" % slo["transitions"])
        for p, st in sorted(slo["final"].items()):
            print("  process %s final: %s (burn rate %sx)"
                  % (p, "BURNING" if st["state"] else "within budget",
                     st.get("burn_rate")))
    bk = agg.get("books")
    if bk:
        print("\n== conservation laws (metrics books) ==")
        print("books_broken transitions: %d%s"
              % (bk["transitions"],
                 "   LATCHED at end of log: %s"
                 % ", ".join(bk["latched"]) if bk["latched"]
                 else "   all laws clear at end of log"))
        for k, d in sorted(bk.get("details", {}).items()):
            print("  %-28s %s" % (k, d))
    pg = agg.get("programs")
    if pg:
        print("\n== program ledger (per-compiled-program perf cards) ==")
        hbm = pg.get("hbm_peak_bytes")
        print("programs: %d   compile total: %.2fs   hbm peak: %s"
              % (pg["count"], pg["compile_s"],
                 "%.1f MiB" % (hbm / float(1 << 20))
                 if hbm is not None else "n/a"))
        print("%-18s %-26s %3s %9s %10s %9s %9s %9s %7s %7s" %
              ("program", "shapes", "n", "compile_s", "GFLOPs",
               "peak_MiB", "pred_ms", "p50_ms", "MFU%", "eff%"))

        def _n(v, scale=1.0, form="%.2f"):
            return "n/a" if v is None else form % (v * scale)

        for r in pg["cards"]:
            print("%-18s %-26s %3d %9.2f %10s %9s %9s %9s %7s %7s" %
                  (r["name"], str(r.get("shapes"))[:26], r["compiles"],
                   r["compile_s"], _n(r["flops"], 1e-9),
                   _n(r["peak_bytes"], 1.0 / (1 << 20), "%.1f"),
                   _n(r["predicted_s"], 1e3),
                   _n(r["measured_p50_ms"]),
                   _n(r["mfu_pct"], form="%.1f"),
                   _n(r["roofline_eff_pct"], form="%.1f")))
            if r.get("status") == "error":
                print("    analysis error: %s" % r.get("error"))
        if pg["top_by_compile"]:
            print("top by compile time: %s"
                  % "  ".join(pg["top_by_compile"]))
        if pg["top_by_gap"]:
            print("largest roofline gap (lowest eff%%): %s"
                  % "  ".join(pg["top_by_gap"]))
    h = agg.get("health", {})
    if h and (h["anomalies"] or h["stalls"] or h["data_corrupt"]
              or h["skipped_batches"]):
        print("\n== health ==")
        print("anomalies: %d  %s" %
              (len(h["anomalies"]),
               " ".join("%s=%d" % kv for kv in
                        sorted(count_by(h["anomalies"], "kind").items()))))
        if h["resolutions"]:
            print("resolutions: %d  %s" %
                  (len(h["resolutions"]),
                   " ".join("%s=%d" % kv for kv in sorted(
                       count_by(h["resolutions"], "ev").items()))))
        if h["stalls"]:
            print("watchdog stalls: %d  %s" %
                  (len(h["stalls"]),
                   " ".join("%s=%d" % kv for kv in sorted(
                       count_by(h["stalls"], "channel").items()))))
        if h["data_corrupt"]:
            print("corrupt data records: %d" % h["data_corrupt"])
        if h["skipped_batches"]:
            print("quarantined batches skipped: %d" % h["skipped_batches"])
        for a in h["unresolved"]:
            print("UNRESOLVED anomaly id=%s kind=%s round=%s batch=%s" %
                  (a.get("id"), a.get("kind"), a.get("round"),
                   a.get("batch")))


def main(argv):
    top = 15
    trace_out = None
    as_json = False
    merge = False
    fleet = False
    want_incidents = False
    paths = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--top" and i + 1 < len(argv):
            top = int(argv[i + 1])
            i += 2
        elif a == "--trace" and i + 1 < len(argv):
            trace_out = argv[i + 1]
            i += 2
        elif a == "--json":
            as_json = True
            i += 1
        elif a == "--merge":
            merge = True
            i += 1
        elif a == "--fleet":
            fleet = True
            i += 1
        elif a == "--incidents":
            want_incidents = True
            i += 1
        elif a.startswith("--"):
            print("unknown option %s" % a, file=sys.stderr)
            return 1
        else:
            paths.append(a)
            i += 1
    many = merge or fleet
    if (len(paths) != 1 and not many) or (many and len(paths) < 1):
        print(__doc__, file=sys.stderr)
        return 1
    for path in paths:
        if not os.path.exists(path):
            print("no such log: %s" % path, file=sys.stderr)
            return 1
    if fleet:
        # router + replica logs: separate processes, relabeled by
        # argument position, joined on the shared trace ids
        events = merge_fleet_shards([load_events(p) for p in paths])
        label = "+".join(paths)
    elif merge:
        events = merge_shards([load_events(p) for p in paths])
        label = "+".join(paths)
    else:
        events = load_events(paths[0])
        label = paths[0]
    agg = aggregate(events)
    if want_incidents:
        # the offline twin of the live /eventz endpoint: t_wall aligns
        # on the earliest shard's wall epoch (single log: its own)
        t0s = [float(ev.get("t0_wall", 0.0)) for ev in events
               if ev.get("ev") == "meta"]
        agg["incidents"] = autopsy.incidents(
            events, t0_wall=min(t0s) if t0s else 0.0)
    if as_json:
        print(json.dumps(agg, indent=1))
    else:
        if fleet:
            print("fleet-merged %d log(s) (shard i = process i): %s\n"
                  % (len(paths), label))
        elif merge:
            print("merged %d shard(s): %s\n" % (len(paths), label))
        print_report(agg, top=top)
        if want_incidents:
            print("\n== incident timeline ==")
            rows = agg["incidents"]
            if not rows:
                print("(no transition or point incidents in this log)")
            for r in rows:
                ev = r["event"]
                detail = " ".join(
                    "%s=%s" % (k, ev[k]) for k in sorted(ev)
                    if k not in ("ev", "ts", "p")
                    and not isinstance(ev[k], (dict, list)))
                print("%10.3fs p=%-3s %-20s %-6s %s"
                      % (r["ts"], ev.get("p", 0), r["kind"],
                         r["state"], detail))
    if trace_out:
        with open(trace_out, "w") as f:
            json.dump(events_to_chrome(events), f)
        print("\nchrome trace written to %s "
              "(open in chrome://tracing or ui.perfetto.dev)" % trace_out)
    unresolved = agg.get("health", {}).get("unresolved", [])
    if unresolved:
        print("%s: %d health_anomaly event(s) with no matching "
              "health_rollback/resolution — the run detected trouble and "
              "never recovered" % (label, len(unresolved)), file=sys.stderr)
        return 2
    open_breakers = (agg.get("serving") or {}).get(
        "breaker_open_unresolved", [])
    if open_breakers:
        print("%s: serving circuit breaker still OPEN at end of log "
              "(process %s) — the run ended shedding every request"
              % (label, ", ".join(open_breakers)), file=sys.stderr)
        return 2
    burning = (agg.get("slo") or {}).get("burning", [])
    if burning:
        print("%s: SLO error-budget burn rate still exceeded at end of "
              "log (process %s) — the run ended blowing its objectives"
              % (label, ", ".join(burning)), file=sys.stderr)
        return 2
    latched = (agg.get("books") or {}).get("latched", [])
    if latched:
        print("%s: conservation law(s) still latched BROKEN at end of "
              "log (%s) — every other number in this report is suspect"
              % (label, ", ".join(latched)), file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
