#!/usr/bin/env python
"""On-device validation of the TPU-only Pallas kernels.

The CPU test suite covers the LRN kernels in interpret mode; the PRNG
kernels (pallas_kernels.uniform / rrelu_mask) use pltpu.prng_random_bits,
which has no CPU interpret path, so this script exercises them on the real
chip: distribution sanity of the uniform draw, the insanity layer's
train-mode forward/backward through the on-core mask, and the Pallas-vs-XLA
LRN numerics compiled for TPU.

Run: python tools/check_tpu_kernels.py   (requires a TPU-backed jax)
"""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np
import jax
import jax.numpy as jnp


def main():
    from cxxnet_tpu.utils import enable_compile_cache
    enable_compile_cache()
    assert jax.default_backend() not in ("cpu",), \
        "this checker needs a TPU backend, got %s" % jax.default_backend()
    from cxxnet_tpu import ops
    from cxxnet_tpu.ops import pallas_kernels
    from cxxnet_tpu.layer import base, layers

    # --- uniform: range, mean/var, determinism per seed ---
    u = np.asarray(jax.jit(
        lambda s: pallas_kernels.uniform(s, (512, 512)))(jnp.int32(7)))
    assert 0.0 <= u.min() and u.max() < 1.0, (u.min(), u.max())
    assert abs(u.mean() - 0.5) < 5e-3, u.mean()
    assert abs(u.var() - 1.0 / 12) < 5e-3, u.var()
    u2 = np.asarray(jax.jit(
        lambda s: pallas_kernels.uniform(s, (512, 512)))(jnp.int32(7)))
    assert np.array_equal(u, u2), "same seed must reproduce"
    u3 = np.asarray(jax.jit(
        lambda s: pallas_kernels.uniform(s, (512, 512)))(jnp.int32(8)))
    assert not np.array_equal(u, u3), "different seed must differ"
    print("uniform kernel: OK (mean=%.4f var=%.4f)" % (u.mean(), u.var()))

    # --- uniform at conv-activation scale: must exceed VMEM (~16 MB) and
    # still compile thanks to the row-block grid ---
    big_shape = (64, 96, 55, 55)  # ~74 MB f32, AlexNet conv1-sized
    ub = np.asarray(jax.jit(
        lambda s: pallas_kernels.uniform(s, big_shape))(jnp.int32(11)))
    assert 0.0 <= ub.min() and ub.max() < 1.0
    assert abs(ub.mean() - 0.5) < 2e-3, ub.mean()
    # per-block reseeding must not repeat the stream across blocks
    flat = ub.reshape(-1)
    assert not np.array_equal(flat[: 2048 * 128],
                              flat[2048 * 128: 2 * 2048 * 128])
    print("uniform kernel large (%.0f MB): OK (mean=%.4f)"
          % (ub.nbytes / 1e6, ub.mean()))

    # --- insanity layer train path through the on-core mask ---
    lay = layers.InsanityLayer()
    lay.set_param("lb", "5")
    lay.set_param("ub", "10")
    x = jnp.asarray(np.random.RandomState(0).randn(8, 16).astype(np.float32))
    ctx = base.ApplyContext(train=True, rng=jax.random.PRNGKey(3))

    def loss(x):
        return jnp.sum(lay.apply({}, [x], ctx)[0])

    out = lay.apply({}, [x], ctx)[0]
    xn = np.asarray(x)
    on = np.asarray(out)
    pos = xn > 0
    assert np.array_equal(on[pos], xn[pos]), "positive part must pass through"
    slope = xn[~pos] / on[~pos]
    assert (slope >= 5 - 1e-3).all() and (slope <= 10 + 1e-3).all(), \
        (slope.min(), slope.max())
    g = np.asarray(jax.grad(loss)(x))
    assert np.array_equal(g[pos], np.ones_like(g[pos]))
    assert ((g[~pos] >= 1 / 10 - 1e-5) & (g[~pos] <= 1 / 5 + 1e-5)).all()
    print("insanity on-core mask: OK (slope in [%.2f, %.2f])"
          % (slope.min(), slope.max()))

    # --- Pallas LRN vs XLA LRN compiled on TPU, f32 + bf16 ---
    x4 = np.random.RandomState(1).randn(4, 32, 14, 14).astype(np.float32)
    for dt, rtol in ((jnp.float32, 1e-4), (jnp.bfloat16, 3e-2)):
        xd = jnp.asarray(x4, dt)
        a = np.asarray(jax.jit(lambda v: pallas_kernels.lrn(
            v, 5, 0.001, 0.75, 1.0))(xd), np.float32)
        b = np.asarray(jax.jit(lambda v: ops.lrn_xla(
            v, 5, 0.001, 0.75, 1.0))(xd), np.float32)
        np.testing.assert_allclose(a, b, rtol=rtol, atol=rtol)
        ga = np.asarray(jax.grad(lambda v: jnp.sum(jnp.square(
            pallas_kernels.lrn(v, 5, 0.001, 0.75, 1.0))))(xd), np.float32)
        gb = np.asarray(jax.grad(lambda v: jnp.sum(jnp.square(
            ops.lrn_xla(v, 5, 0.001, 0.75, 1.0))))(xd), np.float32)
        np.testing.assert_allclose(ga, gb, rtol=rtol * 10, atol=rtol * 10)
        print("pallas lrn vs xla on TPU (%s): OK" % np.dtype(dt).name)

    # --- flash attention: compiled kernels vs dense reference ---
    # tolerance covers the dense reference's default-precision MXU einsums
    from cxxnet_tpu.ops import flash_attn
    from cxxnet_tpu.parallel.ring import attention_reference
    rs = np.random.RandomState(5)
    q = jnp.asarray(rs.randn(2, 4, 512, 64), jnp.float32)
    k = jnp.asarray(rs.randn(2, 4, 512, 64), jnp.float32)
    v = jnp.asarray(rs.randn(2, 4, 512, 64), jnp.float32)
    for causal in (False, True):
        out = np.asarray(flash_attn.flash_attention(q, k, v, causal))
        ref = np.asarray(attention_reference(q, k, v, causal=causal))
        np.testing.assert_allclose(out, ref, rtol=2e-2, atol=2e-2)
        gf = jax.jit(jax.grad(lambda q, k, v: jnp.sum(jnp.sin(
            flash_attn.flash_attention(q, k, v, causal))),
            argnums=(0, 1, 2)))(q, k, v)
        gr = jax.jit(jax.grad(lambda q, k, v: jnp.sum(jnp.sin(
            attention_reference(q, k, v, causal=causal))),
            argnums=(0, 1, 2)))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-2, atol=5e-2)
        print("flash attention on TPU (causal=%s): OK" % causal)
    # sliding window: out-of-window tiles statically skipped, compiled
    outw = np.asarray(flash_attn.flash_attention(
        q, k, v, True, None, False, 96))
    refw = np.asarray(attention_reference(q, k, v, causal=True, window=96))
    np.testing.assert_allclose(outw, refw, rtol=2e-2, atol=2e-2)
    print("flash attention window=96 on TPU: OK")
    # unaligned length: padded tiles + in-kernel tail mask, compiled
    q2 = jnp.asarray(rs.randn(1, 2, 300, 64), jnp.float32)
    out = np.asarray(flash_attn.flash_attention(q2, q2, q2, True))
    ref = np.asarray(attention_reference(q2, q2, q2, causal=True))
    np.testing.assert_allclose(out, ref, rtol=2e-2, atol=2e-2)
    print("flash attention unaligned L=300 on TPU: OK")
    # grouped-query attention: kv heads read via the BlockSpec row map
    kg = jnp.asarray(rs.randn(2, 2, 512, 64), jnp.float32)
    vg = jnp.asarray(rs.randn(2, 2, 512, 64), jnp.float32)
    outg = np.asarray(flash_attn.flash_attention(q, kg, vg, True))
    refg = np.asarray(attention_reference(q, kg, vg, causal=True))
    np.testing.assert_allclose(outg, refg, rtol=2e-2, atol=2e-2)
    gq, gk, gv = jax.jit(jax.grad(lambda q_, k_, v_: jnp.sum(jnp.sin(
        flash_attn.flash_attention(q_, k_, v_, True))),
        argnums=(0, 1, 2)))(q, kg, vg)
    assert gk.shape == kg.shape and gv.shape == vg.shape
    rq, rk, rv = jax.jit(jax.grad(lambda q_, k_, v_: jnp.sum(jnp.sin(
        attention_reference(q_, k_, v_, causal=True))),
        argnums=(0, 1, 2)))(q, kg, vg)
    np.testing.assert_allclose(np.asarray(gq), np.asarray(rq),
                               rtol=5e-2, atol=5e-2)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(rk),
                               rtol=5e-2, atol=5e-2)
    np.testing.assert_allclose(np.asarray(gv), np.asarray(rv),
                               rtol=5e-2, atol=5e-2)
    print("flash attention GQA (4q/2kv heads) on TPU: OK")
    # long-context smoke: L=8192 bf16 train step, O(L) memory
    L = 8192
    qb = jnp.asarray(rs.randn(1, 8, L, 64), jnp.bfloat16)
    g = jax.jit(jax.grad(lambda q: jnp.sum(flash_attn.flash_attention(
        q, qb, qb, True).astype(jnp.float32))))(qb)
    assert np.isfinite(float(jnp.sum(g.astype(jnp.float32))))
    print("flash attention L=8192 bf16 fwd+bwd: OK")

    # --- ring-step flash kernels (CXXNET_RING=flash), compiled ---
    # a 1-device sp mesh exercises the full kernel set (SMEM offsets,
    # aliased carries, dq/dkv accumulators) through Mosaic; multi-device
    # ring semantics are goldened on the CPU mesh (tests/test_ring_flash.py)
    from cxxnet_tpu.parallel import ring as ring_mod
    from jax.sharding import Mesh
    os.environ["CXXNET_RING"] = "flash"
    try:
        mesh1 = Mesh(np.array(jax.devices()[:1]), ("sp",))
        q3 = jnp.asarray(rs.randn(1, 2, 512, 64), jnp.float32)
        for causal in (False, True):
            out = np.asarray(ring_mod.ring_attention(
                q3, q3, q3, mesh1, causal=causal))
            ref = np.asarray(attention_reference(q3, q3, q3, causal=causal))
            np.testing.assert_allclose(out, ref, rtol=2e-2, atol=2e-2)
        g = jax.jit(jax.grad(lambda q: jnp.sum(ring_mod.ring_attention(
            q, q3, q3, mesh1, causal=True))))(q3)
        assert np.isfinite(float(jnp.sum(g)))
        print("ring-flash step kernels compiled (n=1 ring): OK")
    finally:
        os.environ.pop("CXXNET_RING", None)

    # --- channels_last conv-stack layout, compiled on-chip -------------
    # one bf16 train step of a conv->relu->lrn->bn->relu_max_pooling net
    # with channels_last forced BOTH ways; first-conv weights after the
    # step must agree — the on-chip compile/parity smoke for the NHWC
    # paths this chain hits (full per-layer coverage incl. ch_concat and
    # the sibling fusion is tests/test_layout.py on the CPU mesh)
    from cxxnet_tpu.nnet.trainer import Trainer
    from cxxnet_tpu.utils.config import parse_config_string
    from cxxnet_tpu.io.data import DataBatch
    cl_conf = """
netconfig = start
layer[0->1] = conv:k1
  kernel_size = 5
  stride = 2
  nchannel = 32
  random_type = xavier
layer[1->2] = relu
layer[2->3] = lrn
  local_size = 5
  alpha = 0.0001
  beta = 0.75
layer[3->4] = batch_norm:kb
layer[4->5] = relu_max_pooling
  kernel_size = 3
  stride = 2
layer[5->6] = flatten
layer[6->7] = fullc:kf
  nhidden = 10
  init_sigma = 0.01
layer[7->7] = softmax
netconfig = end
input_shape = 3,63,63
batch_size = 16
eta = 0.05
eval_train = 0
compute_dtype = bfloat16
dev = tpu
"""
    db = DataBatch()
    db.data = rs.rand(16, 3, 63, 63).astype(np.float32)
    db.label = (rs.randint(0, 10, (16, 1))).astype(np.float32)
    db.batch_size = 16
    weights = []
    for cl in (0, 1):
        t2 = Trainer()
        for k, v in parse_config_string(
                cl_conf + "channels_last = %d\n" % cl):
            t2.set_param(k, v)
        t2.init_model()
        t2.update(db)
        weights.append(np.asarray(
            jax.device_get(t2.params[0]["wmat"]), np.float32))
    assert np.isfinite(weights[0]).all() and np.isfinite(weights[1]).all()
    # bf16 step, different physical layouts: close, not bitwise
    np.testing.assert_allclose(weights[0], weights[1], rtol=2e-2, atol=2e-4)
    print("channels_last train-step parity on-chip: OK")

    # --- mask-VJP max-pool backward (CXXNET_POOL=mask), compiled --------
    # tie-forcing quantized input; the reference-tie-semantics HLO path
    # must compile and differ from select-and-scatter exactly on ties
    # (the fused Pallas variant was deleted after losing its on-chip A/B
    # 2:1 — onchip_logs/poolab.log)
    from cxxnet_tpu import ops as _ops
    xq = jnp.asarray(np.round(rs.rand(4, 192, 28, 28) * 4) / 4,
                     jnp.bfloat16)
    (_, _), (ph2, pw2) = _ops._pool_padding(30, 30, (3, 3), 1)
    padq = ((1, 1 + ph2), (1, 1 + pw2))
    g_msk = jax.jit(jax.grad(lambda x: jnp.sum(jnp.square(
        _ops._max_pool(x, (3, 3), 1, padq)
    ).astype(jnp.float32))))(xq)
    assert np.isfinite(np.asarray(g_msk, np.float32)).all()
    print("mask-VJP max-pool backward (ties, bf16) compiles on-chip: OK")

    # --- cross-input 1x1 batching parity on-chip ------------------------
    # the opt-in fuse_cross_1x1 path (batched-matmul inception module,
    # net.py _apply_fused_cross) must match the default path through the
    # REAL TPU compiler before tools/cross1x1_ab.py may flip the default
    inc_conf = """
netconfig = start
layer[0->s] = conv:xs
  kernel_size = 3
  pad = 1
  nchannel = 16
  random_type = xavier
layer[s->sa,sb,sc] = split
layer[sa->a1] = conv:xa
  kernel_size = 1
  nchannel = 8
layer[sb->b1] = conv:xb
  kernel_size = 1
  nchannel = 12
layer[sc->c1] = max_pooling
  kernel_size = 3
  stride = 1
  pad = 1
layer[c1->c2] = conv:xp
  kernel_size = 1
  nchannel = 8
layer[a1,b1,c2->cc] = ch_concat
layer[cc->gp] = avg_pooling
  kernel_size = 8
  stride = 8
layer[gp->fl] = flatten
layer[fl->out] = fullc:xh
  nhidden = 5
  init_sigma = 0.05
layer[+0] = softmax
netconfig = end
input_shape = 3,16,16
batch_size = 8
eta = 0.05
eval_train = 0
compute_dtype = bfloat16
dev = tpu
"""
    db2 = DataBatch()
    db2.data = rs.rand(8, 3, 16, 16).astype(np.float32)
    db2.label = rs.randint(0, 5, (8, 1)).astype(np.float32)
    db2.batch_size = 8
    xw = []
    for knob in (0, 1):
        t3 = Trainer()
        for k, v in parse_config_string(
                inc_conf + "fuse_cross_1x1 = %d\n" % knob):
            t3.set_param(k, v)
        t3.init_model()
        if knob:
            assert len(t3.net._cross_1x1_plan()) == 1
        t3.update(db2)
        xw.append(np.asarray(
            jax.device_get(t3.params[0]["wmat"]), np.float32))
    np.testing.assert_allclose(xw[0], xw[1], rtol=2e-2, atol=2e-4)
    print("cross-input 1x1 batching parity on-chip: OK")

    # --- depthwise conv (feature_group_count = C) compiles + steps ------
    # the mobilenet bench row's distinct XLA-TPU path: grouped conv at
    # the one-channel-per-group extreme, under bf16 + channels_last
    from cxxnet_tpu.models import mobilenet_trainer
    mnt = mobilenet_trainer(batch_size=8, input_hw=32, dev="tpu",
                            n_class=10, base_ch=8,
                            blocks=((16, 1), (32, 2)),
                            extra_cfg="eval_train = 0\n"
                                      "compute_dtype = bfloat16\n")
    db3 = DataBatch()
    db3.data = rs.rand(8, 3, 32, 32).astype(np.float32)
    db3.label = rs.randint(0, 10, (8, 1)).astype(np.float32)
    db3.batch_size = 8
    mnt.update(db3)
    assert np.isfinite(np.asarray(
        jax.device_get(mnt.params[0]["wmat"]), np.float32)).all()
    print("depthwise (ngroup=C) conv train step on-chip: OK")

    print("ALL TPU KERNEL CHECKS PASSED")


if __name__ == "__main__":
    main()
