#!/usr/bin/env python
"""im2bin: pack an image list into BinaryPage bins (reference tools/im2bin.cpp).

Usage: im2bin.py <image.lst> <image_root> <out.bin> [page_ints]

Reads lines of ``index label[ label..] filename`` from the list, appends each
image file's raw bytes as one object per record into fixed-size BinaryPages
(default page size matches the reference's 64 MiB pages).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from cxxnet_tpu.utils.binary_page import BinaryPage, KPAGE_INTS


def im2bin(lst_path: str, image_root: str, out_path: str,
           page_ints: int = KPAGE_INTS) -> int:
    count = 0
    with open(out_path, "wb") as fo:
        page = BinaryPage(page_ints)
        with open(lst_path) as f:
            for line in f:
                if not line.strip():
                    continue
                fname = line.split()[-1]
                path = os.path.join(image_root, fname) if image_root else fname
                with open(path, "rb") as fimg:
                    data = fimg.read()
                if not page.push(data):
                    page.save(fo)
                    page.clear()
                    assert page.push(data), \
                        "image %s larger than a page" % fname
                count += 1
        if page.size():
            page.save(fo)
    return count


if __name__ == "__main__":
    if len(sys.argv) < 4:
        print(__doc__)
        sys.exit(1)
    pi = int(sys.argv[4]) if len(sys.argv) > 4 else KPAGE_INTS
    n = im2bin(sys.argv[1], sys.argv[2], sys.argv[3], pi)
    print("packed %d images into %s" % (n, sys.argv[3]))
