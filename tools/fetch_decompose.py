#!/usr/bin/env python
"""Decompose the serving-call round trip through the tunnel: which part
of a predict()/generate() call costs what. The r5 benchall measured
~10-11s PER predict call (any batch size) while a whole 1984-step
generate scan round-tripped in ~1.3s — this pins down whether the cost
is (a) jit cache misses / recompiles, (b) device_put resharding,
(c) the np.asarray fetch path, or (d) eager-op dispatch, and therefore
which number the infer/latency/decode bench rows actually measured.

Usage: python tools/fetch_decompose.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np


def t(label, f, n=3):
    best = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        r = f()
        best = min(best, time.perf_counter() - t0)
    print("%-46s best-of-%d %8.3f s" % (label, n, best), flush=True)
    return r


def main():
    import jax
    import jax.numpy as jnp
    from cxxnet_tpu.utils import enable_compile_cache
    enable_compile_cache()
    from cxxnet_tpu.models import alexnet_trainer
    from cxxnet_tpu.io.data import DataBatch

    batch = 256
    tr = alexnet_trainer(batch_size=batch, input_hw=227, dev="tpu",
                         extra_cfg="eval_train = 0\n"
                                   "compute_dtype = bfloat16\n")
    rs = np.random.RandomState(0)
    b = DataBatch()
    b.data = jax.device_put(rs.rand(batch, 3, 227, 227).astype(np.float32))
    b.label = jax.device_put(np.zeros((batch, 1), np.float32))
    b.batch_size = batch

    print("== warmup (2 predict calls, includes compile) ==", flush=True)
    t("predict warmup", lambda: tr.predict(b), n=2)

    node = tr.net_cfg.param.num_nodes - 1
    fn = tr._jit_cache[("pred", node)]
    print("jit cache sizes: pred=%s" % (fn._cache_size(),), flush=True)

    print("== decomposition ==", flush=True)
    data = t("_shard_batch(batch.data)", lambda: tr._shard_batch(b.data))
    rng = t("_next_rng()", lambda: tr._next_rng())

    def run_pred():
        # the cached program donates arg 0 and returns (pred, params):
        # unpack and adopt the returned alias each iteration, else the
        # 2nd call passes already-donated (deleted) buffers
        pred, new_params = fn(tr.params, data, rng)
        tr._swap_params(new_params)
        return pred

    out = t("jitted pred dispatch (async)", run_pred)
    t("float(jnp.sum(out)) sync", lambda: float(jnp.sum(out)))
    t("np.asarray(out) fetch (batch,)", lambda: np.asarray(out))
    print("jit cache sizes after: pred=%s" % (fn._cache_size(),), flush=True)

    print("== full predict calls (post-warm) ==", flush=True)
    t("tr.predict(b)", lambda: tr.predict(b), n=3)

    # fetch-size scaling: same jitted program, three result sizes
    print("== fetch size scaling (jit identity -> asarray) ==", flush=True)
    for shape in ((256,), (256, 1000), (256, 4096), (1, 1000)):
        x = jax.jit(lambda a: a + 1.0)(jnp.zeros(shape, jnp.float32))
        float(jnp.sum(x))   # ensure computed
        t("np.asarray %s  (%.0f KB)"
          % (shape, np.prod(shape) * 4 / 1024), lambda: np.asarray(x))

    # eager op cost
    print("== eager dispatch ==", flush=True)
    t("eager fold_in", lambda: jax.random.fold_in(jax.random.PRNGKey(0), 3))
    t("eager (x+1) on device",
      lambda: jnp.add(jnp.float32(1.0), jnp.float32(2.0)))

    # decode-loop round trip for the lm rows
    print("== lm generate round trip ==", flush=True)
    from cxxnet_tpu.models import transformer_lm_trainer
    lt = transformer_lm_trainer(vocab=8192, seq=2048, batch_size=8,
                                dim=512, nhead=8, nlayer=4, dev="tpu",
                                extra_cfg="eval_train = 0\n"
                                          "compute_dtype = bfloat16\n")
    prompts = rs.randint(0, 8192, (8, 64))
    t("generate warmup (compile)", lambda: lt.generate(prompts, 1984), n=1)
    t("generate(b8, 1984 new)", lambda: lt.generate(prompts, 1984), n=3)
    t("generate(b8, 64 new)", lambda: lt.generate(prompts, 64), n=3)


if __name__ == "__main__":
    main()
