#!/usr/bin/env python
"""MNIST via the Python numpy API (counterpart of the reference's
example/MNIST/mnist.py over wrapper/cxxnet.py).

Expects the idx .gz files under ./data (see README.md for the download).
"""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "..", ".."))

from cxxnet_tpu import api


def iter_cfg(img, label, batch_size=100, extra=""):
    return """
iter = mnist
  path_img = "%s"
  path_label = "%s"
  batch_size = %d
%s
iter = end
""" % (img, label, batch_size, extra)


NET_CFG = """
netconfig = start
layer[+1:fc1] = fullc:fc1
  nhidden = 100
  init_sigma = 0.01
layer[+1:sg1] = sigmoid:se1
layer[sg1->fc2] = fullc:fc2
  nhidden = 10
  init_sigma = 0.01
layer[+0] = softmax
netconfig = end
input_shape = 1,1,784
batch_size = 100
eta = 0.1
momentum = 0.9
wd = 0.0
metric = error
"""


def main():
    data_dir = sys.argv[1] if len(sys.argv) > 1 else "./data"
    train_iter = api.DataIter(iter_cfg(
        os.path.join(data_dir, "train-images-idx3-ubyte.gz"),
        os.path.join(data_dir, "train-labels-idx1-ubyte.gz"),
        extra="  shuffle = 1"))
    test_iter = api.DataIter(iter_cfg(
        os.path.join(data_dir, "t10k-images-idx3-ubyte.gz"),
        os.path.join(data_dir, "t10k-labels-idx1-ubyte.gz")))
    net = api.train(NET_CFG, train_iter, num_round=15,
                    param={}, eval_data=test_iter)
    print(net.evaluate(test_iter, "final"))


if __name__ == "__main__":
    main()
