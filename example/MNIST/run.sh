#!/bin/bash
# Fetch MNIST and run the 15-round MLP recipe (reference example/MNIST/run.sh).
# Offline (no network): pass --synth to generate a bit-identical-format
# synthetic corpus instead (tests/synth_mnist.py).
set -e
cd "$(dirname "$0")"
REPO=../..

mkdir -p data
if [ "$1" = "--synth" ]; then
    python -c "import sys; sys.path.insert(0, '$REPO/tests'); \
from synth_mnist import make_dataset; make_dataset('data')"
else
    for f in train-images-idx3-ubyte.gz train-labels-idx1-ubyte.gz \
             t10k-images-idx3-ubyte.gz t10k-labels-idx1-ubyte.gz; do
        [ -f "data/$f" ] || \
            wget -P data "https://ossci-datasets.s3.amazonaws.com/mnist/$f"
    done
fi

mkdir -p models
python "$REPO/bin/cxxnet" MNIST.conf
