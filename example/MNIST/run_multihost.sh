#!/bin/bash
# Two-process data-parallel MNIST — the reference's mpi.conf story
# (2 workers on localhost, example/MNIST/mpi.conf) without MPI or
# parameter-server processes: each process contributes its local devices
# to ONE global mesh (jax.distributed over Gloo on CPU, DCN on TPU pods),
# and gradient all-reduce replaces the PS push/pull.
#
# This demo runs on any machine: 2 processes x 4 virtual CPU devices =
# an 8-device global mesh. On a real multi-host TPU pod, drop the two
# exports, point coordinator= at host 0, and set worker_rank per host.
#
# Usage: ./run_multihost.sh   (after ./run.sh or ./run.sh --synth for data)
set -e
cd "$(dirname "$0")"
REPO=../..
[ -f data/train-images-idx3-ubyte.gz ] || { echo "run ./run.sh first"; exit 1; }

export XLA_FLAGS="--xla_force_host_platform_device_count=4"
export CXXNET_JAX_PLATFORM=cpu
COORD=127.0.0.1:9911
# batch 96: the global batch must divide across the 8 mesh devices
ARGS="coordinator=$COORD num_worker=2 dev=cpu:0-7 num_round=3 batch_size=96 model_dir=models_mh"
mkdir -p models_mh

python "$REPO/bin/cxxnet" MNIST.conf $ARGS worker_rank=1 &
W1=$!
python "$REPO/bin/cxxnet" MNIST.conf $ARGS worker_rank=0
wait $W1
echo "multihost run finished"
