#!/usr/bin/env python
"""Train the lm.conf transformer on a synthetic character grammar.

The corpus is deterministic-but-nontrivial: each sequence is a cyclic
alphabet walk with a random phase and stride, so the next character is
exactly predictable from the prefix — a trained causal LM must reach
~100% next-token accuracy, an untrained one sits near 1/vocab.

Usage: python train_lm.py [steps]   (~400 adam steps reach 100%)
"""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.nnet.trainer import Trainer
from cxxnet_tpu.utils.config import ConfigIterator

VOCAB = 28
SEQ = 64


def make_batch(rs, batch=16):
    """Cyclic walks: tok[t] = (phase + stride * t) % VOCAB."""
    phase = rs.randint(0, VOCAB, (batch, 1))
    stride = rs.randint(1, 5, (batch, 1))
    t = np.arange(SEQ + 1)[None, :]
    toks = (phase + stride * t) % VOCAB          # (b, SEQ+1)
    b = DataBatch()
    b.data = toks[:, :SEQ].reshape(batch, 1, 1, SEQ).astype(np.float32)
    b.label = toks[:, 1:].astype(np.float32)     # next-token targets (b, SEQ)
    b.batch_size = batch
    return b


def next_token_accuracy(tr, batch):
    probs = tr.extract_feature(batch, "top[-1]")   # (b, VOCAB, 1, SEQ)
    pred = probs.reshape(probs.shape[0], VOCAB, SEQ).argmax(axis=1)
    # score the second half: the prefix there always determines the walk
    half = SEQ // 2
    return float((pred[:, half:] == batch.label[:, half:]).mean())


def main(steps=400, dev=None):
    conf = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "lm.conf")
    tr = Trainer()
    for k, v in ConfigIterator(conf, ["dev=%s" % dev] if dev else []):
        tr.set_param(k, v)
    tr.init_model()
    rs = np.random.RandomState(0)
    eval_b = make_batch(np.random.RandomState(999))
    print("accuracy before: %.3f" % next_token_accuracy(tr, eval_b))
    for i in range(steps):
        tr.update(make_batch(rs))
        if (i + 1) % 50 == 0:
            print("step %d: accuracy %.3f"
                  % (i + 1, next_token_accuracy(tr, eval_b)))
    acc = next_token_accuracy(tr, eval_b)
    print("final next-token accuracy: %.3f" % acc)
    return acc


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 400)
