#!/usr/bin/env python
"""Train the lm.conf transformer on a synthetic character grammar.

The corpus is deterministic-but-nontrivial: each sequence is a cyclic
alphabet walk with a random phase and stride, so the next character is
exactly predictable from the prefix — a trained causal LM must reach
~100% next-token accuracy, an untrained one sits near 1/vocab.

Usage: python train_lm.py [steps] [conf]   (~400 adam steps reach 100%)

``conf`` defaults to lm.conf; pass lm_pipeline.conf to train the deeper
trunk on the composed pipeline x tensor x data mesh (8 devices — on a
machine without them, prefix
XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu).
"""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.nnet.trainer import Trainer
from cxxnet_tpu.utils.config import ConfigIterator

VOCAB = 28
SEQ = 64


def make_batch(rs, batch=16):
    """Cyclic walks: tok[t] = (phase + stride * t) % VOCAB."""
    phase = rs.randint(0, VOCAB, (batch, 1))
    stride = rs.randint(1, 5, (batch, 1))
    t = np.arange(SEQ + 1)[None, :]
    toks = (phase + stride * t) % VOCAB          # (b, SEQ+1)
    b = DataBatch()
    b.data = toks[:, :SEQ].reshape(batch, 1, 1, SEQ).astype(np.float32)
    b.label = toks[:, 1:].astype(np.float32)     # next-token targets (b, SEQ)
    b.batch_size = batch
    return b


def next_token_accuracy(tr, batch):
    probs = tr.extract_feature(batch, "top[-1]")   # (b, VOCAB, 1, SEQ)
    pred = probs.reshape(probs.shape[0], VOCAB, SEQ).argmax(axis=1)
    # score the second half: the prefix there always determines the walk
    half = SEQ // 2
    return float((pred[:, half:] == batch.label[:, half:]).mean())


def generate(tr, prompts, n_new):
    """Greedy autoregressive continuation of a (batch, prefix_len) prompt
    matrix via the KV-cached decode scan (Trainer.generate — one O(L*d)
    step per token; tests/test_decode.py pins it against the naive
    full-prefix recompute)."""
    return tr.generate(prompts, min(n_new, SEQ - prompts.shape[1]))


def main(steps=400, dev=None, seed=None, conf_name="lm.conf"):
    conf = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        conf_name)
    overrides = []
    if dev:
        overrides.append("dev=%s" % dev)
    if seed is not None:
        overrides.append("seed=%d" % seed)
    tr = Trainer()
    for k, v in ConfigIterator(conf, overrides):
        tr.set_param(k, v)
    tr.init_model()
    rs = np.random.RandomState(0)
    eval_b = make_batch(np.random.RandomState(999))
    print("accuracy before: %.3f" % next_token_accuracy(tr, eval_b))
    for i in range(steps):
        tr.update(make_batch(rs))
        if (i + 1) % 50 == 0:
            print("step %d: accuracy %.3f"
                  % (i + 1, next_token_accuracy(tr, eval_b)))
    acc = next_token_accuracy(tr, eval_b)
    print("final next-token accuracy: %.3f" % acc)
    # greedy generation demo: continue the eval walks from their first half
    half = SEQ // 2
    prompts = np.asarray(eval_b.data).reshape(-1, SEQ)[:, :half].astype(np.int64)
    cont = generate(tr, prompts, half)
    truth = np.concatenate(
        [np.asarray(eval_b.data).reshape(-1, SEQ)[:, half:],
         np.asarray(eval_b.label)[:, -1:]], axis=1)[:, :half]
    gen_acc = float((cont == truth).mean())
    print("greedy generation accuracy over %d tokens: %.3f" % (half, gen_acc))
    return acc


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 400,
         conf_name=sys.argv[2] if len(sys.argv) > 2 else "lm.conf")
