#!/usr/bin/env python
"""Serving demo: train the lm.conf transformer briefly, then exercise
every serving surface on the SAME weights and check they agree token
for token:

1. in-process  — Trainer.generate (KV-cached jitted scan)
2. artifacts   — export_decode -> api.load_decode (prefill/step
                 StableHLO pair, params baked in, jax-only at serving
                 time, versioned CXTF frames)
3. tensor-parallel — the same model served with model_parallel = 2 on
                 a virtual device mesh (weights Megatron-sharded; run
                 with XLA_FLAGS=--xla_force_host_platform_device_count=8
                 JAX_PLATFORMS=cpu to try it without a TPU slice)

Usage: python serve_lm.py [steps]      (default 150; ~100% next-token
accuracy is reached around 400 — serving agreement holds at any step)
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "..", ".."))

# same platform override bin/cxxnet honors: the config route works even
# when a preloaded (tunneled) platform pins JAX_PLATFORMS
_plat = os.environ.get("CXXNET_JAX_PLATFORM")
if _plat:
    import jax
    jax.config.update("jax_platforms", _plat)

import numpy as np

from train_lm import make_batch  # the cyclic-walk corpus


def main():
    steps = int(sys.argv[1]) if len(sys.argv) > 1 else 150
    import jax
    from cxxnet_tpu import api
    from cxxnet_tpu.nnet.trainer import Trainer
    from cxxnet_tpu.utils.config import parse_config_string
    from cxxnet_tpu.utils import serializer

    conf = open(os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "lm.conf")).read()
    tr = Trainer()
    for k, v in parse_config_string(conf):
        tr.set_param(k, v)
    tr.init_model()
    rs = np.random.RandomState(0)
    for s in range(steps):
        tr.update(make_batch(rs, tr.batch_size))
    print("trained %d steps" % steps)

    prompts = np.stack([np.arange(8) % 28, (3 * np.arange(8) + 1) % 28])
    n_new = 8

    # 1. in-process KV-cached generation
    got = tr.generate(prompts, n_new)
    print("in-process generate:", got.tolist())

    # 2. standalone artifacts: prefill + step StableHLO pair
    pre_b, step_b = tr.export_decode(batch_size=2,
                                     prompt_len=prompts.shape[1])
    with tempfile.TemporaryDirectory() as td:
        p1, p2 = os.path.join(td, "pre.hlo"), os.path.join(td, "step.hlo")
        open(p1, "wb").write(pre_b)
        open(p2, "wb").write(step_b)
        gen = api.load_decode(p1, p2)
        got_art = gen(prompts, n_new)
    assert np.array_equal(got_art, got), "artifact loop must match"
    print("artifact decode loop: MATCH")

    # 3. tensor-parallel serving (skipped without >= 2 devices)
    if len(jax.devices()) >= 2:
        w = serializer.Writer()
        tr.save_model(w)
        tr2 = Trainer()
        for k, v in parse_config_string(conf):
            tr2.set_param(k, v)
        tr2.set_param("dev", "%s:0-%d" % (jax.devices()[0].platform,
                                          len(jax.devices()) - 1))
        tr2.set_param("model_parallel", "2")
        tr2.init_model()
        tr2.load_model(serializer.Reader(w.getvalue()))
        got_tp = tr2.generate(prompts, n_new)
        assert np.array_equal(got_tp, got), "tp serving must match"
        print("tensor-parallel serving (mp=2): MATCH")
    else:
        print("tensor-parallel serving: skipped (1 device)")
    print("SERVING DEMO PASSED")


if __name__ == "__main__":
    main()
