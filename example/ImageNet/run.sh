#!/bin/bash
# Pack ImageNet and train AlexNet (reference example/ImageNet/README.md).
# Expects the ILSVRC2012 train set extracted as one directory per synset
# under $IMAGENET_ROOT (obtain via https://image-net.org — registration
# required; not fetchable from this script). Offline: pass --synth for a
# small generated JPEG corpus that exercises the identical pipeline.
set -e
cd "$(dirname "$0")"
REPO=../..

if [ "$1" = "--synth" ]; then
    python - <<'EOF'
import os
import sys
sys.path.insert(0, os.path.join("..", "..", "tests"))
sys.path.insert(0, os.path.join("..", "..", "tools"))
from test_io_image import make_images
from im2bin import im2bin
make_images("imgs", n=2000, n_class=100, hw=256)
lines = open(os.path.join("imgs", "img.lst")).readlines()
open("NameList.train", "w").writelines(lines[:1800])
open("NameList.test", "w").writelines(lines[1800:])
print("packed", im2bin("NameList.train", "imgs", "TRAIN.BIN"), "train /",
      im2bin("NameList.test", "imgs", "TEST.BIN"), "test images")
EOF
    # the stock conf points two directories up (reference layout); derive a
    # local copy pointing at the files we just built
    sed -e 's#\.\./\.\./NameList#./NameList#' -e 's#\.\./\.\./TRAIN#./TRAIN#' \
        -e 's#\.\./\.\./TEST#./TEST#' ImageNet.conf > ImageNet.synth.conf
    mkdir -p models
    python "$REPO/bin/cxxnet" ImageNet.synth.conf max_round=1
    exit 0
fi

: "${IMAGENET_ROOT:?set IMAGENET_ROOT to the extracted train directory}"
# keep all generated artifacts inside this example directory (the stock
# conf's ../../ paths date from the reference's layout) — derive a local
# conf the same way the --synth branch does
python "$REPO/tools/make_imglist.py" "$IMAGENET_ROOT" \
    NameList.train 0.02 NameList.test
python "$REPO/tools/im2bin.py" NameList.train "$IMAGENET_ROOT/" TRAIN.BIN
python "$REPO/tools/im2bin.py" NameList.test "$IMAGENET_ROOT/" TEST.BIN
sed -e 's#\.\./\.\./NameList#./NameList#' -e 's#\.\./\.\./TRAIN#./TRAIN#' \
    -e 's#\.\./\.\./TEST#./TEST#' ImageNet.conf > ImageNet.local.conf
mkdir -p models
python "$REPO/bin/cxxnet" ImageNet.local.conf
