#!/bin/bash
# Prepare the National Data Science Bowl plankton corpus and train
# (reference example/kaggle_bowl/README.md). The raw data needs a Kaggle
# account: download train.zip from
#   https://www.kaggle.com/c/datasciencebowl/data
# into this directory first, then run this script. Offline (no data): pass
# --synth to train the same net on a generated image corpus.
set -e
cd "$(dirname "$0")"
REPO=../..

if [ "$1" = "--synth" ]; then
    python - <<'EOF'
import os
import sys
sys.path.insert(0, os.path.join("..", "..", "tests"))
sys.path.insert(0, os.path.join("..", "..", "tools"))
from test_io_image import make_images
from im2bin import im2bin
# class-colored jpegs stand in for the 121 plankton classes
make_images("imgs", n=1210, n_class=121, hw=48)
lines = open(os.path.join("imgs", "img.lst")).readlines()
open("tr.lst", "w").writelines(lines[:1100])
open("va.lst", "w").writelines(lines[1100:])
print("packed", im2bin("tr.lst", "imgs", "tr.bin"), "train /",
      im2bin("va.lst", "imgs", "va.bin"), "val images")
EOF
    mkdir -p models
    # a short smoke run on the generated corpus; drop the override to
    # train the full 100-round recipe
    python "$REPO/bin/cxxnet" bowl.conf max_round=3
    # prediction + submission leg: raw probabilities over the val pack,
    # assembled into a Kaggle-format CSV (the real leg does the same
    # with test.lst/test.bin and Kaggle's sample_submission.csv)
    python - <<'EOF'
import csv
with open("sample_submission.csv", "w", newline="") as f:
    w = csv.writer(f)
    w.writerow(["image"] + ["class%03d" % i for i in range(121)])
EOF
    sed -e 's/test\.lst/va.lst/' -e 's/test\.bin/va.bin/' \
        -e 's|models/0100\.model|models/0003.model|' pred.conf \
        > pred_synth.conf
    python "$REPO/bin/cxxnet" pred_synth.conf
    python make_submission.py sample_submission.csv va.lst test.txt \
        submission.csv
    head -2 submission.csv
    exit 0
fi

[ -f train.zip ] || { echo "download train.zip from Kaggle first"; exit 1; }
unzip -qn train.zip
# class ids in the submission header's column order, so pred_raw rows
# line up with Kaggle's scored columns
python "$REPO/tools/make_imglist.py" --classes-from sample_submission.csv \
    train tr.lst 0.1 va.lst
python "$REPO/tools/im2bin.py" tr.lst train/ tr.bin
python "$REPO/tools/im2bin.py" va.lst train/ va.bin

mkdir -p models
python "$REPO/bin/cxxnet" bowl.conf

# test-set prediction + submission (needs test.zip unpacked into test/)
if [ -d test ]; then
    python "$REPO/tools/make_imglist.py" --flat test test.lst
    python "$REPO/tools/im2bin.py" test.lst test/ test.bin
    python "$REPO/bin/cxxnet" pred.conf
    python make_submission.py sample_submission.csv test.lst test.txt \
        submission.csv
fi
