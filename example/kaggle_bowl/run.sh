#!/bin/bash
# Prepare the National Data Science Bowl plankton corpus and train
# (reference example/kaggle_bowl/README.md). The raw data needs a Kaggle
# account: download train.zip from
#   https://www.kaggle.com/c/datasciencebowl/data
# into this directory first, then run this script. Offline (no data): pass
# --synth to train the same net on a generated image corpus.
set -e
cd "$(dirname "$0")"
REPO=../..

if [ "$1" = "--synth" ]; then
    python - <<'EOF'
import os
import sys
sys.path.insert(0, os.path.join("..", "..", "tests"))
sys.path.insert(0, os.path.join("..", "..", "tools"))
from test_io_image import make_images
from im2bin import im2bin
# class-colored jpegs stand in for the 121 plankton classes
make_images("imgs", n=1210, n_class=121, hw=48)
lines = open(os.path.join("imgs", "img.lst")).readlines()
open("tr.lst", "w").writelines(lines[:1100])
open("va.lst", "w").writelines(lines[1100:])
print("packed", im2bin("tr.lst", "imgs", "tr.bin"), "train /",
      im2bin("va.lst", "imgs", "va.bin"), "val images")
EOF
    mkdir -p models
    # a short smoke run on the generated corpus; drop the override to
    # train the full 100-round recipe
    python "$REPO/bin/cxxnet" bowl.conf max_round=3
    exit 0
fi

[ -f train.zip ] || { echo "download train.zip from Kaggle first"; exit 1; }
unzip -qn train.zip
python "$REPO/tools/make_imglist.py" train tr.lst 0.1 va.lst
python "$REPO/tools/im2bin.py" tr.lst train/ tr.bin
python "$REPO/tools/im2bin.py" va.lst train/ va.bin

mkdir -p models
python "$REPO/bin/cxxnet" bowl.conf
