#!/usr/bin/env python
"""Assemble the Kaggle NDSB submission CSV from a pred_raw run.

Usage: make_submission.py sample_submission.csv test.lst test.txt out.csv

- sample_submission.csv supplies the header (image column + the 121 class
  names in the order Kaggle scores them — train the model with class ids
  assigned in THAT order, e.g. gen_img_list from the same header).
- test.lst is the image list the pred iterator ran over (index \t label
  \t path); the file's basename becomes the submission image name.
- test.txt is the pred_raw output: one space-separated probability row
  per listed image, same order.

Counterpart of the reference example/kaggle_bowl/make_submission.py
(rewritten; the reference script is python2 and its pred_raw task was
never implemented in the reference binary — see
cxxnet_tpu/learn_task.py task_predict_raw).
"""

import csv
import os
import sys


def main(argv):
    if len(argv) < 4:
        print("Usage: make_submission.py sample_submission.csv test.lst "
              "test.txt out.csv")
        return 1
    with open(argv[0]) as f:
        header = next(csv.reader(f))
    names = []
    with open(argv[1]) as f:
        for line in f:
            # .lst rows are index<TAB>label<TAB>path (space-separated
            # also accepted, matching the iterators' parsing)
            parts = line.rstrip("\n").split("\t")
            if len(parts) == 1:
                parts = line.split()
            names.append(os.path.basename(parts[-1]))
    n_class = len(header) - 1
    wrote = 0
    with open(argv[2]) as fi, open(argv[3], "w", newline="") as fo:
        w = csv.writer(fo)
        w.writerow(header)
        for i, line in enumerate(fi):
            probs = line.split()
            assert len(probs) == n_class, (
                "row %d has %d probabilities, expected %d (submission "
                "header and model nclass disagree?)"
                % (i, len(probs), n_class))
            assert i < len(names), (
                "pred output has more rows than the %d listed images "
                "(stale test.txt from a previous run?)" % len(names))
            w.writerow([names[i]] + probs)
            wrote += 1
    assert wrote == len(names), (
        "pred output has %d rows for %d listed images" % (wrote, len(names)))
    print("wrote %s: %d rows x %d classes" % (argv[3], wrote, n_class))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
